"""Train-step autotuner: HBM estimator (hlo_stats liveness), analytic
memory model, candidate space, search driver, per-layer remat.

The estimator tests run against hand-written HLO (tuple results, TPU tiled
layouts, while/fusion nesting — the shapes that broke earlier parsers) and
one recorded real fixture with its memory_analysis ground truth; the
analytic model is gated by the chip-verified fit/OOM table from bench
rounds r04/r05. Everything here is analysis-only — no TPU, no execution.
"""

import gzip
import json
import os

import pytest

from ray_tpu.autotune.model import (
    POLICY_FLOPS_FACTOR,
    device_hbm_budget_bytes,
    predict_hbm,
    remat_flops_factor,
)
from ray_tpu.autotune.search import (
    AutotuneCache,
    autotune_train_configs,
    geometry_sig,
)
from ray_tpu.autotune.space import Candidate, candidate_space
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.parallel.hlo_stats import (
    _padded_shape_bytes,
    compiled_hbm_bytes,
    hbm_stats,
)

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "hlo")


def _bench_cfg():
    return LlamaConfig(
        vocab_size=32128, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        max_seq_len=2048, tie_embeddings=True, dtype="bfloat16")


# ---------------------------------------------------------------------------
# HBM estimator (hlo_stats.hbm_stats)
# ---------------------------------------------------------------------------

def test_padded_shape_bytes_tiled_layouts():
    # plain: no padding
    assert _padded_shape_bytes("f32[128,256]{1,0}") == 128 * 256 * 4
    # TPU tiling pads the physical dims up to tile multiples:
    # [130, 260] -> [136, 384] under T(8,128)
    assert _padded_shape_bytes("f32[130,260]{1,0:T(8,128)}") == 136 * 384 * 4
    # transposed minor-to-major permutes the physical dims before tiling:
    # {0,1} means dim0 is minor -> physical [260, 130] -> [264, 256]
    assert _padded_shape_bytes("f32[130,260]{0,1:T(8,128)}") == 264 * 256 * 4
    # tuples sum; bf16 is 2 bytes; scalars are itemsize
    assert _padded_shape_bytes("(bf16[8,128]{1,0}, f32[])") == \
        8 * 128 * 2 + 4


def test_hbm_stats_synthetic_straight_line():
    """a and b feed the dot; b dies there, the dot result and a feed the
    ROOT tuple. Peak temp = a + b + dot live together at the dot."""
    hlo = """HloModule m, is_scheduled=true

ENTRY %main (p0: f32[64,64]) -> (f32[64,64], f32[64,64]) {
  %p0 = f32[64,64]{1,0} parameter(0)
  %a = f32[64,64]{1,0} negate(f32[64,64]{1,0} %p0)
  %b = f32[64,64]{1,0} exponential(f32[64,64]{1,0} %p0)
  %d = f32[64,64]{1,0} dot(f32[64,64]{1,0} %a, f32[64,64]{1,0} %b)
  ROOT %t = (f32[64,64]{1,0}, f32[64,64]{1,0}) tuple(f32[64,64]{1,0} %a, f32[64,64]{1,0} %d)
}
"""
    st = hbm_stats(hlo)
    buf = 64 * 64 * 4
    assert st.parameter_bytes == buf
    assert st.peak_temp_bytes == 3 * buf  # a + b + d at the dot
    assert st.n_computations == 1


def test_hbm_stats_tuple_alias_extends_liveness():
    """Buffers packed into a tuple must stay live until the tuple's last
    use — the failure mode that undercounted scan carries 2-3x."""
    hlo = """HloModule m, is_scheduled=true

ENTRY %main (p0: f32[256]) -> f32[256] {
  %p0 = f32[256]{0} parameter(0)
  %a = f32[256]{0} negate(f32[256]{0} %p0)
  %b = f32[256]{0} exponential(f32[256]{0} %p0)
  %t = (f32[256]{0}, f32[256]{0}) tuple(f32[256]{0} %a, f32[256]{0} %b)
  %c = f32[256]{0} add(f32[256]{0} %p0, f32[256]{0} %p0)
  %g = f32[256]{0} get-tuple-element((f32[256]{0}, f32[256]{0}) %t), index=0
  ROOT %r = f32[256]{0} add(f32[256]{0} %g, f32[256]{0} %c)
}
"""
    st = hbm_stats(hlo)
    # a and b stay alive through the tuple -> get-tuple-element chain (the
    # element-level split is deliberately NOT modeled: a GTE keeps the
    # whole tuple's buffers alive — conservative, the safe direction for
    # OOM pruning), so at ROOT: a + b + c + r.
    assert st.peak_temp_bytes == 4 * 256 * 4


def test_hbm_stats_while_body_recursion():
    """A while's peak = live carry + the body's own temp peak; the while
    result aliases its operand (no double count)."""
    hlo = """HloModule m, is_scheduled=true

%cond (p: (f32[1024], s32[])) -> pred[] {
  %p = (f32[1024]{0}, s32[]) parameter(0)
  %i = s32[] get-tuple-element((f32[1024]{0}, s32[]) %p), index=1
  %k = s32[] constant(8)
  ROOT %lt = pred[] compare(s32[] %i, s32[] %k), direction=LT
}

%body (p: (f32[1024], s32[])) -> (f32[1024], s32[]) {
  %p = (f32[1024]{0}, s32[]) parameter(0)
  %x = f32[1024]{0} get-tuple-element((f32[1024]{0}, s32[]) %p), index=0
  %i = s32[] get-tuple-element((f32[1024]{0}, s32[]) %p), index=1
  %big = f32[2048]{0} concatenate(f32[1024]{0} %x, f32[1024]{0} %x), dimensions={0}
  %y = f32[1024]{0} slice(f32[2048]{0} %big), slice={[0:1024]}
  %one = s32[] constant(1)
  %j = s32[] add(s32[] %i, s32[] %one)
  ROOT %r = (f32[1024]{0}, s32[]) tuple(f32[1024]{0} %y, s32[] %j)
}

ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %zero = s32[] constant(0)
  %init = (f32[1024]{0}, s32[]) tuple(f32[1024]{0} %p0, s32[] %zero)
  %w = (f32[1024]{0}, s32[]) while((f32[1024]{0}, s32[]) %init), condition=%cond, body=%body
  ROOT %out = f32[1024]{0} get-tuple-element((f32[1024]{0}, s32[]) %w), index=0
}
"""
    st = hbm_stats(hlo)
    # body peak: big (8 KB) + y (4 KB) + j; entry adds nothing live beyond
    # the aliased carry (parameters are counted separately)
    assert st.peak_temp_bytes >= 2048 * 4 + 1024 * 4
    assert st.peak_temp_bytes < 2 * (2048 * 4 + 1024 * 4)
    assert st.n_computations == 3


def test_hbm_stats_async_tuple_and_tiled_result():
    """Async-start-style nested tuple results with TPU tiled layouts parse
    and price without truncation at the inner parens."""
    hlo = """HloModule m, is_scheduled=true

ENTRY %main (p0: f32[256,128]) -> f32[256,128] {
  %p0 = f32[256,128]{1,0:T(8,128)} parameter(0)
  %s = ((f32[256,128]{1,0:T(8,128)}), (f32[256,128]{1,0:T(8,128)})) custom-call(f32[256,128]{1,0:T(8,128)} %p0), custom_call_target="x"
  %g = f32[256,128]{1,0:T(8,128)} get-tuple-element(((f32[256,128]{1,0:T(8,128)}), (f32[256,128]{1,0:T(8,128)})) %s), index=1
  ROOT %r = f32[256,128]{1,0:T(8,128)} add(f32[256,128]{1,0:T(8,128)} %g, f32[256,128]{1,0:T(8,128)} %g)
}
"""
    st = hbm_stats(hlo)
    buf = 256 * 128 * 4
    assert st.parameter_bytes == buf
    # custom-call result tuple (2 bufs) + ROOT add
    assert st.peak_temp_bytes == 3 * buf


def test_hbm_stats_fixture_within_gate():
    """The 15% acceptance gate on a recorded real train-step module
    (captured by devbench/autotune_bench.py write_fixtures with its
    memory_analysis ground truth)."""
    meta_path = os.path.join(FIXTURE_DIR, "meta.json")
    if not os.path.exists(meta_path):
        pytest.skip("no recorded HLO fixtures")
    meta = json.load(open(meta_path))
    checked = 0
    for name, m in meta.items():
        path = os.path.join(FIXTURE_DIR, f"{name}.hlo.gz")
        if not os.path.exists(path):
            continue
        with gzip.open(path, "rt") as f:
            st = hbm_stats(f.read())
        err = abs(st.peak_bytes - m["measured_total_bytes"]) \
            / m["measured_total_bytes"]
        assert err <= 0.15, f"{name}: estimator off by {err:.1%}"
        # the estimator must overestimate or track closely — an
        # UNDERestimate is the dangerous direction for OOM pruning
        assert st.peak_bytes >= m["measured_total_bytes"] * 0.97, name
        checked += 1
    assert checked >= 3


def test_compiled_hbm_bytes_cpu_memory_analysis():
    """compiled_hbm_bytes prefers the backend's memory_analysis and agrees
    with the text estimator within the documented band."""
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return jnp.tanh(x @ y).sum()

    c = jax.jit(f).lower(jnp.ones((128, 256)), jnp.ones((256, 128))).compile()
    total, source = compiled_hbm_bytes(c)
    assert source == "memory_analysis"
    est = hbm_stats(c.as_text()).peak_bytes
    assert 0.8 <= est / total <= 1.3


# ---------------------------------------------------------------------------
# Analytic memory model
# ---------------------------------------------------------------------------

def test_predict_hbm_chip_verified_boundary():
    """r04/r05 chip ground truth at the 1.1B bench geometry: every config
    that fit must predict under the 15.75 GB v5e budget, every
    compile-time OOM must predict over it."""
    cfg = _bench_cfg()
    budget = 15.75
    fits = [(4, "attn"), (4, "attn+"), (5, "attn"), (8, "attn"),
            (4, "dots")]
    ooms = [(16, "attn"), (8, "dots"), (4, "dots+")]
    for b, r in fits:
        p = predict_hbm(cfg, 2048, Candidate(batch=b, remat=r))
        assert p.total_gb <= budget, f"b{b}/{r}: {p.total_gb} GB (chip fit)"
    for b, r in ooms:
        p = predict_hbm(cfg, 2048, Candidate(batch=b, remat=r))
        assert p.total_gb > budget, f"b{b}/{r}: {p.total_gb} GB (chip OOM)"


def test_predict_hbm_monotonicity():
    cfg = _bench_cfg()

    def gb(**kw):
        return predict_hbm(cfg, 2048, Candidate(**kw)).total_gb

    # batch grows HBM
    assert gb(batch=4, remat="attn") < gb(batch=8, remat="attn")
    # richer save-lists grow HBM
    assert gb(batch=4, remat="full") < gb(batch=4, remat="attn") \
        < gb(batch=4, remat="attn+") < gb(batch=4, remat="dots") \
        < gb(batch=4, remat="dots+")
    # per-layer mix lands between its uniform endpoints
    mix = gb(batch=4, remat="dots:8,attn:8")
    assert gb(batch=4, remat="attn") < mix < gb(batch=4, remat="dots")
    # grad accumulation shrinks activation HBM at fixed batch
    assert gb(batch=16, remat="attn", grad_accum=4) \
        < gb(batch=16, remat="attn", grad_accum=2) \
        < gb(batch=16, remat="attn")
    # zero1 divides optimizer state across data shards
    c = Candidate(batch=8, remat="attn", zero1=True)
    p1 = predict_hbm(cfg, 2048, c, data_shards=1)
    p4 = predict_hbm(cfg, 2048, c, data_shards=4)
    assert p4.components["opt_state"] < p1.components["opt_state"]


def test_remat_flops_factor():
    assert remat_flops_factor("attn", 16) == POLICY_FLOPS_FACTOR["attn"]
    mixed = remat_flops_factor("attn:8,dots:8", 16)
    assert POLICY_FLOPS_FACTOR["dots"] < mixed < POLICY_FLOPS_FACTOR["attn"]


def test_device_hbm_budget_env_override(monkeypatch):
    monkeypatch.setenv("RTPU_HBM_BUDGET_GB", "15.75")
    assert device_hbm_budget_bytes() == int(15.75 * (1 << 30))
    monkeypatch.delenv("RTPU_HBM_BUDGET_GB")
    # CPU host, no override: unknown budget
    assert device_hbm_budget_bytes() is None


# ---------------------------------------------------------------------------
# Candidate space + search driver
# ---------------------------------------------------------------------------

def test_candidate_space_dimensions():
    space = candidate_space(16)
    labels = [c.label for c in space]
    assert len(labels) == len(set(labels))
    assert any(c.zero1 for c in space)
    assert any(c.grad_accum > 1 for c in space)
    assert any("," in c.remat for c in space)          # per-layer specs
    assert any(c.flash_block_q for c in space)
    assert any(c.ce_chunk for c in space)


def test_candidate_env_roundtrip(monkeypatch):
    monkeypatch.setenv("RTPU_FLASH_BLOCK_Q", "64")
    c = Candidate(batch=4, remat="attn", flash_block_q=256, ce_chunk=128)
    with c.applied_env():
        assert os.environ["RTPU_FLASH_BLOCK_Q"] == "256"
        assert os.environ["RTPU_CE_CHUNK"] == "128"
    assert os.environ["RTPU_FLASH_BLOCK_Q"] == "64"
    assert "RTPU_CE_CHUNK" not in os.environ


def test_search_prunes_without_measuring():
    """Candidates predicted over budget are pruned at analysis time: the
    measure callback must NEVER see them (the acceptance criterion: zero
    failed compile-and-run attempts for pruned configs)."""
    cfg = _bench_cfg()
    space = candidate_space(cfg.num_layers)
    budget = int(15.75 * (1 << 30))
    measured = []

    def spy(cand):
        measured.append(cand.label)
        from ray_tpu.autotune.model import predict_hbm as p

        assert p(cfg, 2048, cand).total_bytes <= budget * 1.05
        return {"tokens_per_sec": 100.0}

    res = autotune_train_configs(cfg, 2048, space, hbm_budget_bytes=budget,
                                 measure_fn=spy, max_measure=4)
    assert res.pruned > 0
    assert len(measured) == 4 == res.measured
    pruned_labels = {r["config"] for r in res.trace if r.get("pruned")}
    assert not pruned_labels & set(measured)
    # sweep covers the PR-4 machinery: zero1 / grad-accum / per-layer
    # candidates survive pruning and are in the ranked pool
    kept = {r["config"] for r in res.trace if not r.get("pruned")}
    assert any("/z1" in c for c in kept)
    assert any("/ga" in c for c in kept)
    assert any("|" in c for c in kept)


def test_search_cached_champion_measures_first(tmp_path):
    cfg = _bench_cfg()
    cache = AutotuneCache(path=str(tmp_path / "cache.json"))
    geo = geometry_sig(cfg, 2048, 1)
    champion = "b4/attn+/flash/lowmem"
    cache.put("v5e", geo, champion, {"tokens_per_sec": 16601.4})
    order = []

    def spy(cand):
        order.append(cand.label)
        return {"tokens_per_sec": 10.0}

    res = autotune_train_configs(
        cfg, 2048, candidate_space(cfg.num_layers),
        hbm_budget_bytes=int(15.75 * (1 << 30)), measure_fn=spy,
        max_measure=3, cache=cache, device_kind="v5e")
    assert order[0] == champion
    assert res.measured == 3
    # fresh measurements landed in the cache
    assert cache.get("v5e", geo, order[1])["tokens_per_sec"] == 10.0


def test_search_analysis_only_mode():
    """measure_fn=None: everything priced and ranked, nothing executed —
    the CI smoke path."""
    cfg = _bench_cfg()
    res = autotune_train_configs(
        cfg, 2048, candidate_space(cfg.num_layers),
        hbm_budget_bytes=int(15.75 * (1 << 30)), measure_fn=None)
    assert res.measured == 0
    assert res.winner is not None
    assert res.pruned > 0
    assert all("predicted_hbm_gb" in r for r in res.trace)


def test_search_winner_falls_back_to_cache_on_total_failure(tmp_path):
    cfg = _bench_cfg()
    cache = AutotuneCache(path=str(tmp_path / "cache.json"))
    geo = geometry_sig(cfg, 2048, 1)
    cache.put("v5e", geo, "b4/attn/flash/lowmem",
              {"tokens_per_sec": 16573.5})

    def broken(cand):
        raise RuntimeError("RESOURCE_EXHAUSTED")

    res = autotune_train_configs(
        cfg, 2048, [Candidate(batch=4, remat="attn")],
        hbm_budget_bytes=None, measure_fn=broken, max_measure=2,
        cache=cache, device_kind="v5e")
    assert res.winner == "b4/attn/flash/lowmem"
    assert res.tokens_per_sec == 16573.5
    assert any("error" in r for r in res.trace)
    # failed attempts must not be reported as successful measurements
    assert res.measured == 0 and res.failed == 1


# ---------------------------------------------------------------------------
# Per-layer remat (models/llama.py)
# ---------------------------------------------------------------------------

def test_per_layer_remat_matches_uniform():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import init_params, loss_fn

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    def loss(p, remat):
        return loss_fn(cfg, p, tokens, targets, attn_impl="blockwise",
                       remat=remat)

    base = loss(params, "attn")
    for spec in [("attn", "dots"), "attn:1,dots:1", ("attn", "attn")]:
        np.testing.assert_allclose(float(loss(params, spec)), float(base),
                                   rtol=1e-6, atol=1e-6)
    g1 = jax.grad(lambda p: loss(p, "attn"))(params)
    g2 = jax.grad(lambda p: loss(p, ("dots", "attn")))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_per_layer_remat_validation():
    from ray_tpu.models.llama import normalize_remat

    with pytest.raises(ValueError, match="per-layer remat"):
        normalize_remat(("attn",), 2)
    assert normalize_remat("attn:2", 2) == "attn"      # uniform collapses
    assert normalize_remat(("attn", "dots"), 2) == ("attn", "dots")
    assert normalize_remat("dots", 2) == "dots"
    assert normalize_remat(True, 2) is True


# ---------------------------------------------------------------------------
# End-to-end measure path (tiny geometry, CPU, one AOT compile)
# ---------------------------------------------------------------------------

def test_measure_fn_records_hbm_provenance():
    """bench._make_measure_fn: AOT compile + memory-analysis provenance +
    a real timed step, at test-size geometry."""
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from bench import _make_measure_fn

    cfg = LlamaConfig.tiny()
    measure = _make_measure_fn(cfg, 32, steps=2, warmup=1)
    m = measure(Candidate(batch=2, remat="attn", attn="blockwise",
                          grad_accum=2, zero1=True))
    assert m["tokens_per_sec"] > 0
    assert m["measured_hbm_gb"] and m["measured_hbm_gb"] > 0
    assert m["hbm_source"] in ("memory_analysis", "hlo_liveness")
