"""Tuner: the experiment driver.

Capability parity with the reference's Tuner/TuneController (reference:
python/ray/tune/tuner.py:43 Tuner; execution/tune_controller.py:67 — the
actor-based trial event loop: launch trials up to the concurrency limit,
poll step results, consult the scheduler, apply PBT exploit/explore by
checkpoint transfer between trial actors).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import ray_tpu
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import Trainable, TrialActor, wrap_function
from ray_tpu.tune.trial import Trial


@dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    search_alg: Searcher | None = None
    scheduler: TrialScheduler | None = None
    seed: int | None = None


@dataclass
class TuneResult:
    config: dict
    metrics: dict
    error: str | None = None
    checkpoint: Any = None
    trial_id: str = ""

    @property
    def metrics_dataframe(self):  # lazy import; optional pandas-free use
        return self.metrics


@dataclass
class ResultGrid:
    results: list[TuneResult] = field(default_factory=list)
    metric: str | None = None
    mode: str = "max"

    def __len__(self) -> int:
        return len(self.results)

    def __getitem__(self, i: int) -> TuneResult:
        return self.results[i]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TuneResult:
        metric = metric or self.metric
        mode = mode or self.mode
        scored = [r for r in self.results
                  if r.error is None and metric in r.metrics]
        if not scored:
            raise RuntimeError("no successful trial reported the metric")
        key = (lambda r: r.metrics[metric])
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    @property
    def errors(self) -> list[str]:
        return [r.error for r in self.results if r.error]


class Tuner:
    """Drive an experiment of trials over a search space.

    ``trainable`` may be: a function(config), a Trainable subclass, or a
    train.DataParallelTrainer instance (runs under tune, reference §3.4 /
    M2 nesting).
    """

    def __init__(self, trainable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: Any = None,
                 stop: dict | None = None,
                 trial_resources: dict | None = None):
        self._trainable_cls = _as_trainable_cls(trainable)
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        self.stop = stop or {}
        self.trial_resources = trial_resources or {"CPU": 1}

    def fit(self) -> ResultGrid:
        ray_tpu.init()
        tc = self.tune_config
        searcher = tc.search_alg or BasicVariantGenerator(seed=tc.seed)
        scheduler = tc.scheduler or FIFOScheduler()
        searcher.set_search_properties(tc.metric, tc.mode, self.param_space)
        scheduler.set_search_properties(tc.metric, tc.mode)

        trials: list[Trial] = []
        exhausted = False
        # Pre-generate for the basic generator so num_samples semantics match
        # the reference (grid × samples).
        if isinstance(searcher, BasicVariantGenerator):
            target = searcher.total_variants(tc.num_samples)
        else:
            target = tc.num_samples

        max_conc = tc.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 4)))

        RemoteTrial = ray_tpu.remote(TrialActor)

        def launch(trial: Trial, checkpoint=None):
            start_iter = trial.last_result.get("training_iteration", 0)
            trial.actor = RemoteTrial.options(
                num_cpus=self.trial_resources.get("CPU", 1),
                resources={k: v for k, v in self.trial_resources.items()
                           if k != "CPU"} or None,
            ).remote(self._trainable_cls, trial.config, checkpoint, start_iter)
            trial.status = Trial.RUNNING
            trial.pending_step = trial.actor.train_step.remote()

        def finish(trial: Trial, status: str, error: str | None = None):
            trial.status = status
            trial.error = error
            if trial.actor is not None:
                try:
                    # Unblock any report()-parked user thread, then kill.
                    trial.actor.stop.remote()
                    ray_tpu.kill(trial.actor)
                except Exception:
                    pass
                trial.actor = None
            trial.pending_step = None

        while True:
            # Admit new trials.
            running = [t for t in trials if t.status == Trial.RUNNING]
            while (not exhausted and len(trials) < target
                   and len(running) < max_conc):
                trial_id = f"t{len(trials)}"
                cfg = searcher.suggest(trial_id)
                if cfg is None:
                    exhausted = True
                    break
                trial = Trial(cfg, trial_id=trial_id)
                trials.append(trial)
                launch(trial)
                running.append(trial)

            if not running:
                if exhausted or len(trials) >= target:
                    break
                time.sleep(0.01)
                continue

            # Poll outstanding steps.
            ref_to_trial = {t.pending_step: t for t in running}
            ready, _ = ray_tpu.wait(list(ref_to_trial), num_returns=1,
                                    timeout=5.0)
            for ref in ready:
                trial = ref_to_trial[ref]
                try:
                    result = ray_tpu.get(ref)
                except Exception as e:
                    searcher.on_trial_complete(trial.trial_id, error=True)
                    scheduler.on_trial_complete(trial, None)
                    finish(trial, Trial.ERROR, error=repr(e))
                    continue
                if set(result) - {"done", "training_iteration"}:
                    trial.last_result = {**trial.last_result, **result}
                trial.results.append(result)
                searcher.on_trial_result(trial.trial_id, result)

                if result.get("done") or self._hit_stop(result):
                    searcher.on_trial_complete(trial.trial_id, result)
                    scheduler.on_trial_complete(trial, result)
                    # Capture the final checkpoint before tearing down.
                    try:
                        trial.checkpoint = ray_tpu.get(trial.actor.save.remote())
                    except Exception:
                        pass
                    finish(trial, Trial.TERMINATED)
                    continue

                decision = scheduler.on_trial_result(trial, result)
                if decision == TrialScheduler.STOP:
                    searcher.on_trial_complete(trial.trial_id, result)
                    scheduler.on_trial_complete(trial, result)
                    try:
                        trial.checkpoint = ray_tpu.get(trial.actor.save.remote())
                    except Exception:
                        pass
                    finish(trial, Trial.TERMINATED)
                    continue

                if trial.pbt_request is not None:
                    self._apply_pbt(trial, launch)
                    continue

                trial.pending_step = trial.actor.train_step.remote()

        return ResultGrid(
            results=[TuneResult(config=t.config,
                                metrics=t.last_result,
                                error=t.error,
                                checkpoint=t.checkpoint,
                                trial_id=t.trial_id)
                     for t in trials],
            metric=tc.metric, mode=tc.mode)

    def _hit_stop(self, result: dict) -> bool:
        return any(k in result and result[k] >= v for k, v in self.stop.items())

    def _apply_pbt(self, trial: Trial, launch) -> None:
        """Exploit+explore: clone donor checkpoint into this trial with the
        perturbed config (reference: pbt.py _exploit via checkpoint
        transfer)."""
        req, trial.pbt_request = trial.pbt_request, None
        donor: Trial = req["donor"]
        new_config: dict = req["config"]
        checkpoint = None
        if donor.actor is not None:
            try:
                checkpoint = ray_tpu.get(donor.actor.save.remote())
            except Exception:
                checkpoint = donor.checkpoint
        trial.config = new_config
        try:
            ray_tpu.kill(trial.actor)
        except Exception:
            pass
        trial.restarts += 1
        launch(trial, checkpoint)


def _as_trainable_cls(trainable) -> type:
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable) and not hasattr(trainable, "fit"):
        return wrap_function(trainable)
    if hasattr(trainable, "fit"):
        # A Trainer instance: run its fit() as a single-step function trial,
        # threading trial config into train_loop_config (reference: Train-
        # under-Tune nesting, SURVEY §2.3 M2).
        trainer = trainable

        def trainer_fn(config: dict):
            import copy

            t = copy.copy(trainer)
            merged = dict(t.train_loop_config or {})
            merged.update(config.get("train_loop_config", config))
            t.train_loop_config = merged
            res = t.fit()
            from ray_tpu.tune.trainable import report

            report(dict(res.metrics or {}))

        return wrap_function(trainer_fn)
    raise TypeError(f"not a trainable: {trainable!r}")
