"""Core-runtime microbenchmarks — the runtime-health envelope.

Mirrors the reference's microbenchmark suite shape (reference:
python/ray/_private/ray_perf.py, published numbers in
release/perf_metrics/microbenchmark.json — reproduced in BASELINE.md): actor
call rates, task throughput, object put/get rates and bandwidth, wait fan-in,
placement-group churn. Results are written to PERF.json with the reference
baseline beside each row.

Hardware note recorded in the output: the reference numbers come from
multi-core m5/m6i-class instances; this harness reports `nproc` so ratios can
be read in context (head + daemons + driver + workers share the same cores).

Run: python bench_core.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID

# Reference values from BASELINE.md (release/perf_metrics/microbenchmark.json).
BASELINES = {
    "1_1_actor_calls_sync": (1645.0, "calls/s"),
    "1_1_actor_calls_async": (7528.0, "calls/s"),
    "1_n_actor_calls_async": (6982.0, "calls/s"),
    "n_n_actor_calls_async": (22975.0, "calls/s"),
    "single_client_tasks_sync": (751.0, "tasks/s"),
    "single_client_tasks_async": (5781.0, "tasks/s"),
    "multi_client_tasks_async": (18575.0, "tasks/s"),
    "single_client_put_calls": (4552.0, "puts/s"),
    "single_client_get_calls": (10155.0, "gets/s"),
    "single_client_put_gigabytes": (10.94, "GB/s"),
    "single_client_wait_1k_refs": (4.27, "ops/s"),
    "placement_group_create/removal": (589.0, "PGs/s"),
    # Reference: 1 GiB broadcast to 50 nodes in 16.72 s (BASELINE.md,
    # scalability/object_store.json) = 2.99 GB/s aggregate delivery on a
    # 50-node AWS cluster. Here: 128 MB to 4 fake nodes on one box —
    # aggregate delivered GB/s, relay-distributed with bounded source
    # egress (runtime._pick_copy).
    "object_store_broadcast": (2.99, "GB/s aggregate"),
}

CLUSTER = None  # set by main(); bench_broadcast adds nodes to it


def timeit(name, fn, multiplier=1, min_time=2.0):
    """Run fn repeatedly for ~min_time, return ops/sec (reference harness
    shape: ray_perf.py timeit)."""
    # Two warmup rounds: the first may fork workers (slow), the second runs
    # against the warmed pool.
    fn()
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    print(f"  {name}: {rate:,.1f}", file=sys.stderr)
    return rate


@remote
def noop(*_args):
    return None


@remote
class Counter:
    def __init__(self):
        self.n = 0

    def tick(self):
        self.n += 1
        return self.n

    def noop(self):
        return None


def bench_actor_calls_sync():
    a = Counter.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)  # ensure started
    rate = timeit("1_1_actor_calls_sync", lambda: ray_tpu.get(a.noop.remote()))
    ray_tpu.kill(a)
    return rate


def bench_actor_calls_async(batch=200):
    a = Counter.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)
    def op():
        ray_tpu.get([a.noop.remote() for _ in range(batch)])
    rate = timeit("1_1_actor_calls_async", op, multiplier=batch)
    ray_tpu.kill(a)
    return rate


def bench_1_n_actor_calls(n=4, batch=100):
    actors = [Counter.remote() for _ in range(n)]
    ray_tpu.get([a.noop.remote() for a in actors], timeout=120)
    def op():
        refs = []
        for a in actors:
            refs.extend(a.noop.remote() for _ in range(batch))
        ray_tpu.get(refs)
    rate = timeit("1_n_actor_calls_async", op, multiplier=n * batch)
    for a in actors:
        ray_tpu.kill(a)
    return rate


def bench_n_n_actor_calls(n=4, batch=100):
    actors = [Counter.remote() for _ in range(n)]
    ray_tpu.get([a.noop.remote() for a in actors], timeout=120)

    def client(i):
        refs = [actors[i].noop.remote() for _ in range(batch)]
        ray_tpu.get(refs)

    def op():
        threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    rate = timeit("n_n_actor_calls_async", op, multiplier=n * batch)
    for a in actors:
        ray_tpu.kill(a)
    return rate


def bench_tasks_sync():
    ray_tpu.get(noop.remote(), timeout=60)
    return timeit("single_client_tasks_sync", lambda: ray_tpu.get(noop.remote()))


def bench_tasks_async(batch=500):
    ray_tpu.get(noop.remote(), timeout=60)
    def op():
        ray_tpu.get([noop.remote() for _ in range(batch)])
    return timeit("single_client_tasks_async", op, multiplier=batch)


def bench_multi_client_tasks(n=4, batch=250):
    ray_tpu.get(noop.remote(), timeout=60)

    def client():
        ray_tpu.get([noop.remote() for _ in range(batch)])

    def op():
        threads = [threading.Thread(target=client) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    return timeit("multi_client_tasks_async", op, multiplier=n * batch)


def bench_put_calls():
    payload = b"x" * 100
    return timeit("single_client_put_calls", lambda: ray_tpu.put(payload))


def bench_get_calls():
    ref = ray_tpu.put(b"x" * 100)
    return timeit("single_client_get_calls",
                  lambda: [ray_tpu.get(ref) for _ in range(100)],
                  multiplier=100)


def bench_put_gigabytes():
    arr = np.zeros(64 * 1024 * 1024, dtype=np.uint8)  # 64 MB
    nbytes = arr.nbytes

    def op():
        ref = ray_tpu.put(arr)
        del ref

    rate = timeit("single_client_put_gigabytes", op, multiplier=1, min_time=3.0)
    return rate * nbytes / 1e9


def bench_wait_1k_refs():
    refs = [ray_tpu.put(i) for i in range(1000)]

    def op():
        ready, _ = ray_tpu.wait(refs, num_returns=1000)
        assert len(ready) == 1000

    return timeit("single_client_wait_1k_refs", op, min_time=2.0)


def bench_broadcast():
    from ray_tpu.core.worker import global_worker
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    rt = global_worker.runtime
    size = 128 * 1024 * 1024
    n_nodes = 4
    added = [CLUSTER.add_node(num_cpus=1, node_id=f"bcast-{i}")
             for i in range(n_nodes)]
    try:
        @remote
        def consume(blob):
            return len(blob)

        def fan_out():
            big = ray_tpu.put(b"b" * size)
            refs = [consume.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=f"bcast-{i}"), num_cpus=1).remote(big)
                for i in range(n_nodes)]
            assert ray_tpu.get(refs, timeout=300) == [size] * n_nodes

        fan_out()  # warm worker forks
        # Best-of-3: the build box is a shared VM whose effective memory
        # bandwidth swings ~2x between runs — a single draw benchmarks the
        # noisy neighbor, not the data plane.
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fan_out()
            best = min(best, time.perf_counter() - t0)
        return n_nodes * size / best / 1e9
    finally:
        for d in added:
            try:
                CLUSTER.remove_node(d)
            except Exception:
                pass


def bench_pg_churn():
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    def op():
        pg = placement_group([{"CPU": 0.01}], strategy="PACK")
        pg.wait(timeout=30)
        remove_placement_group(pg)

    return timeit("placement_group_create/removal", op, min_time=2.0)


def main():
    quick = "--quick" in sys.argv
    os.environ.setdefault("RTPU_WORKER_IDLE_TTL_S", "300")
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())

    global CLUSTER
    c = Cluster()
    CLUSTER = c
    # 4 CPUs bounds the worker pool: on a small host every extra worker
    # process costs real latency (all cluster processes share the cores).
    c.add_node(num_cpus=4)
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"

    # Warm the worker pool before measuring (reference:
    # HandlePrestartWorkers + ray_perf's own warmup): a Python worker boot
    # costs ~1 s of CPU, and measuring through fork storms benchmarks the
    # fork, not the runtime.
    try:
        rt._daemon.call("prestart_workers", n=4, timeout=10)
    except Exception:
        pass
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        ray_tpu.get([noop.remote() for _ in range(200)], timeout=60)
        ks = list(rt._key_states.values())
        if sum(len(k.workers) for k in ks) >= 4:
            break

    suite = [
        ("single_client_put_calls", bench_put_calls),
        ("single_client_get_calls", bench_get_calls),
        ("single_client_put_gigabytes", bench_put_gigabytes),
        ("single_client_wait_1k_refs", bench_wait_1k_refs),
        ("single_client_tasks_sync", bench_tasks_sync),
        ("single_client_tasks_async", bench_tasks_async),
        ("multi_client_tasks_async", bench_multi_client_tasks),
        ("1_1_actor_calls_sync", bench_actor_calls_sync),
        ("1_1_actor_calls_async", bench_actor_calls_async),
        ("1_n_actor_calls_async", bench_1_n_actor_calls),
        ("n_n_actor_calls_async", bench_n_n_actor_calls),
        ("placement_group_create/removal", bench_pg_churn),
        ("object_store_broadcast", bench_broadcast),
    ]
    rows = []
    try:
        for name, fn in suite:
            try:
                value = fn()
            except Exception as e:  # noqa: BLE001
                print(f"  {name} FAILED: {e}", file=sys.stderr)
                value = 0.0
            base, unit = BASELINES[name]
            rows.append({
                "name": name,
                "value": round(value, 2),
                "unit": unit,
                "baseline": base,
                "ratio": round(value / base, 3) if base else None,
            })
    finally:
        try:
            rt.shutdown()
            c.shutdown()
        except Exception:
            pass

    out = {
        "hardware": {"nproc": os.cpu_count(),
                     "note": "reference numbers are from multi-core m5/m6i "
                             "instances; this box shares all cluster "
                             "processes on nproc cores",
                     "variance": "shared/steal-heavy VM: single-thread "
                                 "memcpy swings ~0.45-1.7 GB/s between "
                                 "runs, so cross-run row deltas below ~2x "
                                 "are host weather, not code"},
        "rows": rows,
    }
    with open("PERF.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
