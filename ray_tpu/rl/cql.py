"""CQL: conservative Q-learning — offline RL over logged transitions.

Capability parity with the reference's offline value-based family
(reference: rllib/algorithms/cql/cql.py — CQL adds a conservative
regularizer to the TD loss so Q-values of actions absent from the dataset
are pushed DOWN, preventing the offline-RL failure mode where argmax-Q
exploits overestimated out-of-distribution actions). Discrete CQL(H):

    loss = TD_huber + alpha * mean( logsumexp_a Q(s, a) - Q(s, a_data) )

The dataset is a ray_tpu.data Dataset with obs/actions/rewards/next_obs/
dones columns (the same layout BC and the replay buffer use); batches
stream through iter_batches, the update is jitted, and a target network
tracks the online net like DQN's.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.env import make_env
from ray_tpu.rl.ppo import init_mlp, mlp_apply
from ray_tpu.tune.trainable import Trainable


@partial(jax.jit, static_argnums=(0,))
def cql_update(optimizer, params, target_params, opt_state, batch,
               gamma, alpha):
    def loss_fn(p):
        q = mlp_apply(p, batch["obs"])                       # [B, A]
        q_sa = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
        q_next = mlp_apply(target_params, batch["next_obs"]).max(-1)
        target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(q_next)
        td = optax.huber_loss(q_sa, target).mean()
        # Conservative gap: how far OOD actions sit above the data action.
        gap = (jax.nn.logsumexp(q, axis=-1) - q_sa).mean()
        return td + alpha * gap, (td, gap)

    (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    td, gap = aux
    return optax.apply_updates(params, updates), opt_state, td, gap


@dataclass
class CQLConfig:
    env: str = "CartPole-v1"           # spaces + optional evaluation
    dataset: Any = None                # obs/actions/rewards/next_obs/dones
    lr: float = 1e-3
    gamma: float = 0.99
    alpha: float = 1.0                 # conservative-regularizer weight
    batch_size: int = 256
    epochs_per_step: int = 1
    target_update_every: int = 32      # updates between target-net syncs
    hidden: int = 64
    evaluation_episodes: int = 0
    seed: int = 0
    extra: dict = field(default_factory=dict)

    def build(self) -> "CQL":
        return CQL({"cql_config": self})


class CQL(Trainable):
    """Offline conservative Q-learning (reference: cql.py)."""

    def setup(self, config: dict) -> None:
        cfg = config.get("cql_config") or CQLConfig(
            **{k: v for k, v in config.items()
               if k in CQLConfig.__dataclass_fields__})
        if cfg.dataset is None:
            raise ValueError("CQLConfig.dataset is required (offline data)")
        self.cfg = cfg
        probe = make_env(cfg.env, seed=cfg.seed)
        self.params = init_mlp(
            jax.random.PRNGKey(cfg.seed),
            [probe.observation_size, cfg.hidden, cfg.hidden,
             probe.num_actions])
        self.target_params = self.params
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._updates = 0

    def step(self) -> dict:
        cfg = self.cfg
        td_sum = gap_sum = 0.0
        seen = 0
        for _ in range(cfg.epochs_per_step):
            for batch in cfg.dataset.iter_batches(
                    batch_size=cfg.batch_size,
                    local_shuffle_buffer_size=4 * cfg.batch_size,
                    local_shuffle_seed=cfg.seed + self.iteration):
                jb = {
                    "obs": jnp.asarray(np.asarray(batch["obs"], np.float32)),
                    "actions": jnp.asarray(
                        np.asarray(batch["actions"], np.int32)),
                    "rewards": jnp.asarray(
                        np.asarray(batch["rewards"], np.float32)),
                    "next_obs": jnp.asarray(
                        np.asarray(batch["next_obs"], np.float32)),
                    "dones": jnp.asarray(
                        np.asarray(batch["dones"], np.float32)),
                }
                self.params, self.opt_state, td, gap = cql_update(
                    self.optimizer, self.params, self.target_params,
                    self.opt_state, jb, cfg.gamma, cfg.alpha)
                n = len(jb["actions"])
                td_sum += float(td) * n
                gap_sum += float(gap) * n
                seen += n
                self._updates += 1
                if self._updates % cfg.target_update_every == 0:
                    self.target_params = self.params
        denom = max(seen, 1)
        out = {"td_loss": td_sum / denom,
               "conservative_gap": gap_sum / denom,
               "num_samples_trained": seen}
        if cfg.evaluation_episodes > 0:
            out["episode_return_mean"] = self._evaluate(
                cfg.evaluation_episodes)
        return out

    def _evaluate(self, episodes: int) -> float:
        returns = []
        env = make_env(self.cfg.env, seed=self.cfg.seed + 10_000)
        for _ in range(episodes):
            obs = env.reset()
            total, done, steps = 0.0, False, 0
            while not done and steps < 1000:
                a = int(np.asarray(
                    mlp_apply(self.params, jnp.asarray(obs[None]))
                ).argmax(-1)[0])
                obs, r, term, trunc = env.step(a)
                done = term or trunc
                total += r
                steps += 1
            returns.append(total)
        return float(np.mean(returns))

    def save_checkpoint(self) -> Any:
        return {"params": jax.tree.map(np.asarray, self.params),
                "target_params": jax.tree.map(np.asarray, self.target_params),
                "updates": self._updates, "iteration": self.iteration}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, checkpoint["params"])
        self.target_params = jax.tree.map(jnp.asarray,
                                          checkpoint["target_params"])
        self._updates = checkpoint["updates"]
        self.iteration = checkpoint["iteration"]
