"""SharedMemoryStore: Python client for the native shm object store.

Capability parity with the reference's plasma client (reference:
src/ray/object_manager/plasma/client.h — create/seal/get/release/delete over
a shared arena; fd-backed zero-copy buffers). Clients attach to the node's
segment by name; ``get`` returns a zero-copy memoryview over the mapped
segment. Spill-on-OOM: create asks the store for LRU candidates, spills
them to disk, deletes, and retries (reference:
local_object_manager.h:135 SpillObjectUptoMaxThroughput).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading

from ray_tpu._native import load_library

_ID_SIZE = 20

OK = 0
ERR_EXISTS = -1
ERR_NOT_FOUND = -2
ERR_OOM = -3
ERR_NOT_SEALED = -4
ERR_BUSY = -5


class ShmStoreError(RuntimeError):
    pass


def _lib():
    lib = load_library("objstore", ["objstore/objstore.cc"])
    if not hasattr(lib.store_create, "_configured"):
        P = ctypes.c_void_p
        u64 = ctypes.c_uint64
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.store_create.restype = P
        lib.store_create.argtypes = [ctypes.c_char_p, u64, u64]
        lib.store_open.restype = P
        lib.store_open.argtypes = [ctypes.c_char_p]
        lib.store_close.argtypes = [P]
        lib.store_destroy.argtypes = [ctypes.c_char_p]
        lib.store_create_object.restype = ctypes.c_int
        lib.store_create_object.argtypes = [P, u8p, u64, ctypes.POINTER(u64)]
        lib.store_seal.restype = ctypes.c_int
        lib.store_seal.argtypes = [P, u8p]
        lib.store_get.restype = ctypes.c_int
        lib.store_get.argtypes = [P, u8p, ctypes.POINTER(u64),
                                  ctypes.POINTER(u64)]
        lib.store_get_partial.restype = ctypes.c_int
        lib.store_get_partial.argtypes = [P, u8p, ctypes.POINTER(u64),
                                          ctypes.POINTER(u64),
                                          ctypes.POINTER(u64)]
        lib.store_set_progress.restype = ctypes.c_int
        lib.store_set_progress.argtypes = [P, u8p, u64]
        lib.store_abort.restype = ctypes.c_int
        lib.store_abort.argtypes = [P, u8p]
        lib.store_release.restype = ctypes.c_int
        lib.store_release.argtypes = [P, u8p]
        lib.store_contains.restype = ctypes.c_int
        lib.store_contains.argtypes = [P, u8p]
        lib.store_delete.restype = ctypes.c_int
        lib.store_delete.argtypes = [P, u8p]
        lib.store_evict_candidates.restype = ctypes.c_int
        lib.store_evict_candidates.argtypes = [P, u64, u8p, ctypes.c_int]
        lib.store_stats.argtypes = [P, ctypes.POINTER(u64), ctypes.POINTER(u64),
                                    ctypes.POINTER(u64)]
        lib.store_create._configured = True
    return lib


def _id_buf(object_id: bytes):
    if len(object_id) != _ID_SIZE:
        # Hash-pad arbitrary ids to the fixed wire size.
        import hashlib
        object_id = hashlib.sha1(object_id).digest()
    return (ctypes.c_uint8 * _ID_SIZE).from_buffer_copy(object_id)


class SharedMemoryStore:
    """One per node (created by the node daemon); workers attach with
    ``create=False``."""

    def __init__(self, name: str, capacity_bytes: int = 1 << 28,
                 create: bool = True, spill_dir: str | None = None,
                 num_slots: int = 4096):
        self._libh = _lib()
        self.name = name if name.startswith("/") else f"/{name}"
        if create:
            self._h = self._libh.store_create(self.name.encode(),
                                              capacity_bytes, num_slots)
        else:
            self._h = self._libh.store_open(self.name.encode())
        if not self._h:
            raise ShmStoreError(
                f"could not {'create' if create else 'open'} shm store "
                f"{self.name!r}")
        self._created = create
        # Map the segment in Python for zero-copy reads/writes.
        fd = os.open(f"/dev/shm{self.name}", os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        self._spill_dir = spill_dir or f"/tmp/ray_tpu/shm_spill{self.name}"
        self._spilled: dict[bytes, str] = {}
        self._lock = threading.Lock()
        self._closed = False

    # -- object API --

    def _alloc(self, object_id: bytes, size: int) -> int | None:
        """Allocate an unsealed entry, spilling LRU objects on OOM. Returns
        the arena offset, or None when the object already exists."""
        idb = _id_buf(bytes(object_id))
        off = ctypes.c_uint64()
        for _ in range(3):
            rc = self._libh.store_create_object(self._h, idb, size,
                                                ctypes.byref(off))
            if rc == OK:
                return off.value
            if rc == ERR_EXISTS:
                return None
            if rc == ERR_OOM:
                if not self._spill(size):
                    raise ShmStoreError(
                        f"object of {size} bytes does not fit "
                        f"(capacity {self.stats()['capacity']})")
                continue
            raise ShmStoreError(f"create failed rc={rc}")
        raise ShmStoreError(f"object of {size} bytes does not fit")

    def put(self, object_id: bytes, data) -> None:
        """Create+write+seal. Spills LRU objects on OOM."""
        self.put_parts(object_id, [data])

    def put_parts(self, object_id: bytes, parts) -> None:
        """Scatter-write: allocate once, memcpy each buffer directly into the
        arena (skips the concatenation copy a single-``bytes`` put needs —
        reference: plasma CreateAndSeal with out-of-band pickle5 buffers)."""
        parts = [memoryview(p).cast("B") for p in parts]
        size = sum(len(p) for p in parts)
        pos = self._alloc(object_id, size)
        if pos is None:
            return  # idempotent
        idb = _id_buf(bytes(object_id))
        start = pos
        for p in parts:
            self._mm[pos:pos + len(p)] = p
            pos += len(p)
            # Publish the watermark as each buffer lands: cut-through
            # readers (the transfer plane) can start serving a multi-part
            # put before the final seal.
            self._libh.store_set_progress(self._h, idb, pos - start)
        self._libh.store_seal(self._h, idb)

    def create(self, object_id: bytes, size: int) -> memoryview:
        """Allocate an unsealed entry and return a writable view into the
        arena — chunked transfers write received pieces straight into place
        (one memcpy total; reference: plasma Create→write→Seal protocol).
        Call seal() when every byte is written."""
        off = self._alloc(object_id, size)
        if off is None:
            raise ShmStoreError("object already exists")
        return memoryview(self._mm)[off:off + size]

    def seal(self, object_id: bytes) -> None:
        self._libh.store_seal(self._h, _id_buf(bytes(object_id)))

    def set_progress(self, object_id: bytes, watermark: int) -> None:
        """Advance the sealed-range watermark of an unsealed entry: bytes
        [0, watermark) are valid and may be served to cut-through readers
        (monotone; seal() raises it to the full size). Chunked transfers
        call this as contiguous ranges land so the node can relay the
        object while its own pull is still in flight."""
        self._libh.store_set_progress(self._h, _id_buf(bytes(object_id)),
                                      watermark)

    def progress(self, object_id: bytes) -> tuple[int, int] | None:
        """(total_size, watermark) for a present entry — sealed or still
        mid-transfer — or None when absent/aborted. The probe that lets a
        second same-node reader wait for an in-flight pull instead of
        starting a duplicate one."""
        idb = _id_buf(bytes(object_id))
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        mark = ctypes.c_uint64()
        rc = self._libh.store_get_partial(self._h, idb, ctypes.byref(off),
                                          ctypes.byref(size),
                                          ctypes.byref(mark))
        if rc != OK:
            return None
        self._libh.store_release(self._h, idb)
        return size.value, mark.value

    def get_partial(self, object_id: bytes) -> tuple[memoryview, int]:
        """Pinned view over a possibly-unsealed entry plus its watermark:
        only [0, watermark) is valid. Caller must release(object_id). Used
        by the RPC chunk server to serve ranges cut-through."""
        idb = _id_buf(bytes(object_id))
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        mark = ctypes.c_uint64()
        rc = self._libh.store_get_partial(self._h, idb, ctypes.byref(off),
                                          ctypes.byref(size),
                                          ctypes.byref(mark))
        if rc != OK:
            raise KeyError(object_id)
        return (memoryview(self._mm)[off.value:off.value + size.value],
                mark.value)

    def abort(self, object_id: bytes) -> None:
        """Drop a failed in-flight transfer. Unlike delete(), safe while
        cut-through readers still pin the entry: memory is reclaimed by
        the last release, and new lookups see 'missing' immediately."""
        self._libh.store_abort(self._h, _id_buf(bytes(object_id)))

    def get(self, object_id: bytes) -> memoryview:
        """Zero-copy view; call release(object_id) when done."""
        idb = _id_buf(bytes(object_id))
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._libh.store_get(self._h, idb, ctypes.byref(off),
                                  ctypes.byref(size))
        if rc == ERR_NOT_FOUND:
            restored = self._restore(bytes(object_id))
            if restored is None:
                raise KeyError(object_id)
            rc = self._libh.store_get(self._h, idb, ctypes.byref(off),
                                      ctypes.byref(size))
        if rc == ERR_NOT_SEALED:
            # Mid-write by another process: indistinguishable from "not
            # here yet" for a reader — callers poll/retry on KeyError.
            raise KeyError(object_id)
        if rc != OK:
            raise ShmStoreError(f"get failed rc={rc}")
        return memoryview(self._mm)[off.value:off.value + size.value]

    def get_view(self, object_id: bytes) -> "ArenaView":
        """Pinned zero-copy view (see ArenaView): the object stays
        refcounted in the arena until the view (or anything borrowing its
        buffer, e.g. a zero-copy numpy array) is garbage-collected."""
        return ArenaView(self, bytes(object_id), self.get(object_id))

    def get_bytes(self, object_id: bytes) -> bytes:
        view = self.get(object_id)
        try:
            return bytes(view)
        finally:
            view.release()
            self.release(object_id)

    def release(self, object_id: bytes) -> None:
        self._libh.store_release(self._h, _id_buf(bytes(object_id)))

    def size(self, object_id: bytes) -> int | None:
        """Size probe without copying the payload out of the arena."""
        try:
            view = self.get(object_id)
        except Exception:
            return None
        try:
            return len(view)
        finally:
            view.release()
            self.release(object_id)

    def contains(self, object_id: bytes) -> bool:
        if self._libh.store_contains(self._h, _id_buf(bytes(object_id))):
            return True
        with self._lock:
            return self._hashed(object_id) in self._spilled

    def delete(self, object_id: bytes) -> None:
        rc = self._libh.store_delete(self._h, _id_buf(bytes(object_id)))
        if rc == ERR_BUSY:
            raise ShmStoreError("object is pinned (refcount > 0)")
        with self._lock:
            path = self._spilled.pop(self._hashed(object_id), None)
        if path and os.path.exists(path):
            os.unlink(path)

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        n = ctypes.c_uint64()
        self._libh.store_stats(self._h, ctypes.byref(cap), ctypes.byref(used),
                               ctypes.byref(n))
        return {"capacity": cap.value, "used": used.value,
                "num_objects": n.value,
                "num_spilled": len(self._spilled)}

    # -- spill/restore --

    def _hashed(self, object_id: bytes) -> bytes:
        object_id = bytes(object_id)
        if len(object_id) != _ID_SIZE:
            import hashlib
            return hashlib.sha1(object_id).digest()
        return object_id

    def _spill(self, bytes_needed: int) -> bool:
        max_out = 64
        buf = (ctypes.c_uint8 * (_ID_SIZE * max_out))()
        n = self._libh.store_evict_candidates(
            self._h, max(bytes_needed, 1), buf, max_out)
        if n <= 0:
            return False
        os.makedirs(self._spill_dir, exist_ok=True)
        for i in range(n):
            oid = bytes(buf[i * _ID_SIZE:(i + 1) * _ID_SIZE])
            idb = _id_buf(oid)
            off = ctypes.c_uint64()
            size = ctypes.c_uint64()
            if self._libh.store_get(self._h, idb, ctypes.byref(off),
                                    ctypes.byref(size)) != OK:
                continue
            path = os.path.join(self._spill_dir, oid.hex())
            try:
                with open(path, "wb") as f:
                    f.write(self._mm[off.value:off.value + size.value])
            finally:
                self._libh.store_release(self._h, idb)
            if self._libh.store_delete(self._h, idb) == OK:
                with self._lock:
                    self._spilled[oid] = path
            else:
                os.unlink(path)
        return True

    def _restore(self, object_id: bytes) -> bool | None:
        oid = self._hashed(object_id)
        with self._lock:
            path = self._spilled.get(oid)
        if path is None or not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            data = f.read()
        self.put(object_id, data)
        with self._lock:
            self._spilled.pop(oid, None)
        os.unlink(path)
        return True

    # -- lifecycle --

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._mm.close()
        self._libh.store_close(self._h)

    def destroy(self) -> None:
        self.close()
        self._libh.store_destroy(self.name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ArenaView:
    """A pinned window into the shm arena: holds the store refcount (so
    spill/eviction skip the object) and the mmap buffer until released or
    garbage-collected. Exposes the buffer protocol (PEP 688), so
    np.frombuffer(ArenaView(...)) builds a ZERO-COPY array whose base
    keeps the pin alive — the reference's plasma get() returns read-only
    arrays with exactly this lifetime contract."""

    __slots__ = ("view", "_store", "_oid", "_released")

    def __init__(self, store: SharedMemoryStore, object_id: bytes,
                 view: memoryview):
        self.view = view
        self._store = store
        self._oid = object_id
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        try:
            self.view.release()
        finally:
            try:
                self._store.release(self._oid)
            except Exception:
                pass

    def __del__(self):  # noqa: D105
        self.release()

    def __buffer__(self, flags):  # PEP 688 (Python >= 3.12)
        # READ-ONLY: consumers must not be able to flip writeable back on
        # and mutate the sealed object in the shared arena under every
        # other process holding the ref.
        return memoryview(self.view).toreadonly()

    def __len__(self) -> int:
        return len(self.view)

    def __bool__(self) -> bool:
        return True
