"""Podracer RL throughput proof: legacy EnvRunner vs Anakin vs Sebulba.

Emits PERF_RL.json with env-steps/sec for the three PPO substrates at a
MATCHED geometry (same total envs, same unroll length, same network and
minibatch/epoch hyperparameters — every path consumes the same batch per
update):

- legacy: the Python EnvRunnerGroup path — one jitted policy call plus N
  Python env.step()s per vector step, host GAE + jitted update.
- anakin: the whole loop fused into one jitted program (rl/anakin.py) —
  vmap envs x scan unroll x scan iters, zero host round-trips inside a
  train call. Benched at one device: this host has a single core, so the
  8-virtual-device pmap only serializes replicated work (the multi-device
  axis is correctness-tested in tests/test_rl_vec.py and earns its keep
  on real meshes).
- sebulba: streaming actors (rl/sebulba.py) — jitted rollouts on actor
  processes, trajectory blocks through the object plane, learner-side
  prefetch thread, bounded staleness window.

The geometry leans small-net/single-epoch deliberately: the SGD update is
identical work in all three paths, so it bounds any speedup from above —
the bench sizes it to the env-stepping cost the paths actually differ in.

Acceptance gates (dryrun asserts these):
- anakin_speedup_vs_legacy >= 10x
- sebulba_speedup_vs_legacy >= 3x
- learning sanity: CartPole return improves in BOTH fast paths.

Geometry overrides: RTPU_RL_NUM_ENVS / RTPU_RL_UNROLL_LEN (registry of
record: utils/config.py "RL vectorized Podracer paths").

Run: python devbench/rl_bench.py [--quick]
Quick mode (wired into `python __graft_entry__.py dryrun_multichip`) uses
the same geometry with fewer repetitions and lands under "quick_refresh"
in an existing PERF_RL.json — the committed full-run provenance is never
overwritten.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT_PATH = os.path.join(REPO, "PERF_RL.json")


def _geometry() -> dict:
    num_envs = int(os.environ.get("RTPU_RL_NUM_ENVS", 512))
    unroll = int(os.environ.get("RTPU_RL_UNROLL_LEN", 64))
    return {"env": "CartPole-v1", "num_envs": num_envs,
            "unroll_len": unroll, "hidden": 8, "num_epochs": 1,
            "num_minibatches": 4}


def _timed_steps(algo, calls: int, trials: int) -> dict:
    """Best steps/sec over `trials` runs of `calls` train steps each
    (single-core box: best-of damps scheduler interference)."""
    best = 0.0
    returns = []
    for _ in range(trials):
        steps = 0
        t0 = time.monotonic()
        for _ in range(calls):
            m = algo.train_step()
            steps += m["num_env_steps_sampled"]
            returns.append(round(m["episode_return_mean"], 2))
        best = max(best, steps / (time.monotonic() - t0))
    return {"timed_calls": calls, "trials": trials,
            "env_steps_per_call": steps,
            "env_steps_per_sec": round(best, 1), "returns": returns}


def _sanity(algo, steps: int) -> float:
    best = 0.0
    for _ in range(steps):
        best = max(best, algo.train_step()["episode_return_mean"])
    return best


def _bench_legacy(geo: dict, quick: bool) -> dict:
    from ray_tpu.rl.ppo import PPOConfig

    algo = PPOConfig(env=geo["env"], num_env_runners=0,
                     num_envs_per_runner=geo["num_envs"],
                     rollout_len=geo["unroll_len"], hidden=geo["hidden"],
                     num_epochs=geo["num_epochs"],
                     num_minibatches=geo["num_minibatches"], seed=0).build()
    try:
        warm = algo.train_step()  # jit the policy + update once
        out = _timed_steps(algo, 2 if quick else 3, 2)
        out["first_return"] = round(warm["episode_return_mean"], 2)
        return out
    finally:
        algo.cleanup()


def _bench_anakin(geo: dict, quick: bool) -> dict:
    from ray_tpu.rl.ppo import PPOConfig

    # Same iters_per_step in quick mode: at 4 iters the per-call host
    # overhead (pmap dispatch + metric fetch) halves the measured rate
    # and the quick gate flakes under the 10x bar.
    iters = 8
    devices = int(os.environ.get("RTPU_RL_ANAKIN_DEVICES", 1))
    algo = PPOConfig(env=geo["env"], vectorized=True,
                     num_envs=geo["num_envs"],
                     unroll_len=geo["unroll_len"], hidden=geo["hidden"],
                     num_epochs=geo["num_epochs"],
                     num_minibatches=geo["num_minibatches"], seed=0,
                     extra={"iters_per_step": iters,
                            "anakin_devices": devices}).build()
    try:
        t0 = time.monotonic()
        warm = algo.train_step()  # compiles the fused program
        compile_s = time.monotonic() - t0
        out = _timed_steps(algo, 2 if quick else 3, 2)
        best = max(out["returns"] + [_sanity(algo, 6 if quick else 10)])
        out.update({
            "iters_per_step": iters,
            "compile_seconds": round(compile_s, 2),
            "num_devices": algo._engine.num_devices,
            "first_return": round(warm["episode_return_mean"], 2),
            "best_return": round(best, 2),
        })
        return out
    finally:
        algo.cleanup()


def _bench_sebulba(geo: dict, quick: bool) -> dict:
    import ray_tpu
    from ray_tpu.rl.ppo import PPOConfig

    runners = 2
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, resources={"TPU": 4.0})
    try:
        algo = PPOConfig(env=geo["env"], vectorized=True,
                         num_env_runners=runners,
                         num_envs_per_runner=geo["num_envs"] // runners,
                         unroll_len=geo["unroll_len"],
                         hidden=geo["hidden"],
                         num_epochs=geo["num_epochs"],
                         num_minibatches=geo["num_minibatches"],
                         seed=0).build()
        try:
            warm = algo.train_step()  # actor rollouts + learner compile
            out = _timed_steps(algo, 3 if quick else 6, 1 if quick else 2)
            best = max(out["returns"]
                       + [_sanity(algo, 8 if quick else 40)])
            m = algo._engine
            out.update({
                "num_env_runners": runners,
                "first_return": round(warm["episode_return_mean"], 2),
                "best_return": round(best, 2),
                "weight_version": m.weight_version,
                "dropped_stale": m.dropped_stale,
            })
            return out
        finally:
            algo.cleanup()
    finally:
        ray_tpu.shutdown()


def run_bench(quick: bool = False, out_path: str = OUT_PATH) -> dict:
    geo = _geometry()
    legacy = _bench_legacy(geo, quick)
    anakin = _bench_anakin(geo, quick)
    sebulba = _bench_sebulba(geo, quick)

    a_speed = anakin["env_steps_per_sec"] / legacy["env_steps_per_sec"]
    s_speed = sebulba["env_steps_per_sec"] / legacy["env_steps_per_sec"]
    # Learning sanity: strict improvement over the untrained first call.
    # Margins are per-path: Anakin packs iters_per_step updates into each
    # call; Sebulba advances one weight version per call, so quick mode
    # sees few updates and the margin is correspondingly small.
    a_margin = 1.0 if quick else 10.0
    s_margin = 0.5 if quick else 3.0
    result = {
        "bench": "rl_podracer",
        "quick": quick,
        "geometry": geo,
        "legacy_envrunner": legacy,
        "anakin": anakin,
        "sebulba": sebulba,
        "acceptance": {
            "anakin_speedup_vs_legacy": round(a_speed, 2),
            "sebulba_speedup_vs_legacy": round(s_speed, 2),
            "anakin_ge_10x": a_speed >= 10.0,
            "sebulba_ge_3x": s_speed >= 3.0,
            "anakin_learns": anakin["best_return"]
                >= anakin["first_return"] + a_margin,
            "sebulba_learns": sebulba["best_return"]
                >= sebulba["first_return"] + s_margin,
        },
    }
    # Quick dryrun refreshes land under "quick_refresh", never overwriting
    # full-run provenance (same namespacing contract as PERF_MULTISLICE /
    # PERF_PIPELINE / PERF_GOODPUT quick rows). Returns the fresh result
    # either way (callers assert on it; the file keeps the provenance).
    doc = result
    if quick and os.path.exists(out_path):
        try:
            existing = json.load(open(out_path))
        except Exception:
            existing = {}
        if not existing.get("quick"):
            existing["quick_refresh"] = result
            doc = existing
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    return result


if __name__ == "__main__":
    core = run_bench(quick="--quick" in sys.argv)
    print(json.dumps({
        "legacy_steps_per_sec":
            core["legacy_envrunner"]["env_steps_per_sec"],
        "anakin_steps_per_sec": core["anakin"]["env_steps_per_sec"],
        "sebulba_steps_per_sec": core["sebulba"]["env_steps_per_sec"],
        "acceptance": core["acceptance"],
    }, indent=1))
