"""ClusterRuntime: the per-process core-worker library for cluster mode.

Capability parity with the reference's core_worker (reference:
src/ray/core_worker/core_worker.cc — SubmitTask :1957 lease-based submission
with worker reuse via NormalTaskSubmitter, Put :971 / Get :1290 owner-based
object resolution, SubmitActorTask :2372 direct gRPC to the actor's worker):
every process (driver or pooled worker) instantiates one ClusterRuntime. It
owns a local object store, serves object fetches to peers, submits tasks via
node-daemon leases, and talks to the head for actors/KV/named entities.

Object protocol: the submitting worker *owns* task returns. Small results
ride inline in the task reply and are stored at the owner (reference:
max_direct_call_object_size); large results stay at the executor, the owner
records the location, and readers fetch from the holder.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from ray_tpu.core.cluster.protocol import (
    AsyncRpcClient,
    EventLoopThread,
    RpcClient,
    RpcError,
    RpcServer,
)
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.store import LocalObjectStore, ReferenceCounter
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.utils import serialization
from ray_tpu.utils.config import get_config
from ray_tpu.utils.ids import ActorID, NodeID, ObjectID, WorkerID

import cloudpickle


class _LeasedWorker:
    def __init__(self, lease_id: str, worker_id: str, addr: tuple[str, int],
                 client: AsyncRpcClient):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.addr = addr
        self.client = client
        self.inflight = 0
        self.idle_since = 0.0  # monotonic ts when inflight last hit 0


class ClusterRuntime:
    """Runtime interface implementation backed by the cluster."""

    MAX_INFLIGHT_PER_WORKER = 16

    # Results below this size travel inline / in the process-local store;
    # larger blobs go through the node's shared-memory arena when available
    # (reference: plasma for non-inline objects).
    SHM_THRESHOLD = 32 * 1024

    def __init__(self, head_host: str, head_port: int,
                 node_daemon_addr: tuple[str, int] | None = None,
                 is_worker: bool = False, shm_name: str | None = None):
        self.worker_id = WorkerID.from_random()
        self.node_id = NodeID.from_random()
        self.is_worker = is_worker
        self.store = LocalObjectStore()
        self.refs = ReferenceCounter(on_release=self._release_object)
        # Attach the node's shm arena (created by the node daemon).
        self.shm = None
        shm_name = shm_name or os.environ.get("RTPU_SHM_NAME")
        if shm_name:
            try:
                from ray_tpu.core.shm_store import SharedMemoryStore

                self.shm = SharedMemoryStore(shm_name, create=False)
            except Exception:
                self.shm = None
        self._locations: dict[ObjectID, str] = {}  # owned oid -> holder worker hex
        self._io = EventLoopThread.get()
        self.head = RpcClient(head_host, head_port)
        self._head_host, self._head_port = head_host, head_port
        self.node_daemon_addr = node_daemon_addr
        self._daemon = RpcClient(*node_daemon_addr) if node_daemon_addr else None
        # Leases per scheduling key (reference: normal_task_submitter.h:52).
        self._leases: dict[tuple, list[_LeasedWorker]] = {}
        self._lease_lock = threading.Lock()
        self._peer_clients: dict[tuple[str, int], RpcClient] = {}
        self._peer_lock = threading.Lock()
        self._actor_addr_cache: dict[str, tuple[str, int]] = {}
        self._actor_queues: dict[str, Any] = {}
        self._actor_queue_lock = threading.Lock()
        self._actor_states: dict[str, str] = {}
        self._cancelled: set[ObjectID] = set()
        self._shutdown = False

        # Serve object fetches (and, for workers, task execution) to peers.
        self.server = RpcServer("127.0.0.1", 0)
        self.server.register("get_object", self._handle_get_object)
        self.server.register("free_object", self._handle_free_object)
        self.server.register("report_location", self._handle_report_location)
        self.server.register("ping", self._handle_ping)
        self.addr = self._io.run(self.server.start())
        self.head.call("register_worker", worker_id=self.worker_id.hex(),
                       host=self.addr[0], port=self.addr[1])
        threading.Thread(target=self._lease_reaper, daemon=True,
                         name="lease-reaper").start()
        # Actor state invalidation via pubsub.
        self.head.aio.on_notify("pub", self._on_pub)
        self.head.call("subscribe", channel="actor_events")

    # ------------------------------------------------------------------ serving
    async def _handle_ping(self, conn, **kw):
        return {"ok": True, "worker_id": self.worker_id.hex()}

    async def _handle_get_object(self, conn, oid: str, timeout: float = 10.0):
        object_id = ObjectID.from_hex(oid)
        import asyncio

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._local_contains(object_id):
                data = await asyncio.get_running_loop().run_in_executor(
                    None, self._local_blob, object_id
                )
                if data is not None:
                    return {"data": data}
            holder = self._locations.get(object_id)
            if holder is not None:
                return {"location": holder}
            await asyncio.sleep(0.01)
        return {"pending": True}

    async def _handle_free_object(self, conn, oid: str):
        # Owner-directed free: drop every local copy, including the node
        # arena's (the owner has decided the object is dead).
        object_id = ObjectID.from_hex(oid)
        self.store.delete(object_id)
        if self.shm is not None:
            try:
                self.shm.delete(object_id.binary())
            except Exception:
                pass
        return {"ok": True}

    async def _handle_report_location(self, conn, oid: str, holder: str):
        self._locations[ObjectID.from_hex(oid)] = holder
        return {"ok": True}

    async def _on_pub(self, channel: str, payload: dict):
        if channel == "actor_events":
            aid = payload.get("actor_id")
            state = payload.get("state")
            self._actor_states[aid] = state
            if state == "ALIVE" and payload.get("addr"):
                self._actor_addr_cache[aid] = tuple(payload["addr"])
            elif state in ("DEAD", "RESTARTING"):
                self._actor_addr_cache.pop(aid, None)

    # ------------------------------------------------------------------ peers
    def _peer(self, addr: tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        with self._peer_lock:
            cli = self._peer_clients.get(addr)
            if cli is None:
                cli = RpcClient(*addr)
                self._peer_clients[addr] = cli
            return cli

    def _resolve_worker_addr(self, worker_hex: str) -> tuple[str, int] | None:
        res = self.head.call("resolve_worker", worker_id=worker_hex)
        return tuple(res["addr"]) if res.get("addr") else None

    # ------------------------------------------------------------------ put/get
    def _release_object(self, oid: ObjectID, rec=None) -> None:
        self.store.delete(oid)
        # The shm arena is shared node-wide: only the object's owner may
        # delete from it — a borrower releasing its cache must not GC data
        # other processes still reference (reference: owner-driven GC,
        # reference_counter.h).
        owns = rec is not None and rec.owner_id == self.worker_id
        if owns and self.shm is not None:
            try:
                self.shm.delete(oid.binary())
            except Exception:
                pass

    def _store_blob(self, oid: ObjectID, blob: bytes, owner) -> None:
        """Large blobs land in the node shm arena (visible to every local
        process, zero-copy); small ones in the process-local store."""
        if self.shm is not None and len(blob) >= self.SHM_THRESHOLD:
            try:
                self.shm.put(oid.binary(), blob)
                return
            except Exception:
                pass  # arena full and unspillable: fall back
        self.store.put(oid, blob, owner)

    def _local_blob(self, oid: ObjectID) -> bytes | None:
        if self.store.contains(oid):
            return self.store.get(oid)
        if self.shm is not None:
            try:
                return self.shm.get_bytes(oid.binary())
            except KeyError:
                pass
        return None

    def _local_contains(self, oid: ObjectID) -> bool:
        if self.store.contains(oid):
            return True
        return self.shm is not None and self.shm.contains(oid.binary())

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.worker_id)
        self._store_blob(oid, serialization.serialize(value), self.worker_id)
        self.refs.add_owned(oid, self.worker_id)
        return ObjectRef(oid, self.worker_id)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            data = self._fetch(ref, deadline)
            value = serialization.deserialize(data)
            if isinstance(value, (TaskError, ActorDiedError, TaskCancelledError)):
                raise value
            out.append(value)
        return out

    def _fetch(self, ref: ObjectRef, deadline: float | None) -> bytes:
        # 1. local (process store, then node shm arena)
        local = self._local_blob(ref.id)
        if local is not None:
            return local
        owner_hex = ref.owner_id.hex() if ref.owner_id else None
        am_owner = ref.owner_id == self.worker_id
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            if am_owner:
                # Block on the store's seal event (inline results land there);
                # wake periodically to check for a large-result location report.
                holder = self._locations.get(ref.id)
                if holder is not None:
                    data = self._fetch_from_holder(holder, ref)
                    if data is not None:
                        return data
                    time.sleep(0.01)
                    continue
                step = 0.1 if remaining is None else min(0.1, remaining)
                try:
                    return self.store.get(ref.id, timeout=step)
                except TimeoutError:
                    # A local worker may have deposited the result in the
                    # node arena rather than our process store.
                    if self.shm is not None:
                        try:
                            return self.shm.get_bytes(ref.id.binary())
                        except KeyError:
                            pass
                    continue
            # borrower: ask the owner
            if owner_hex is None:
                raise ObjectLostError(ref.hex(), "ref has no owner")
            addr = self._resolve_worker_addr(owner_hex)
            if addr is None:
                raise ObjectLostError(ref.hex(), "owner not found (OwnerDied)")
            try:
                res = self._peer(addr).call("get_object", oid=ref.hex(),
                                            timeout=min(remaining or 10.0, 10.0) + 5)
            except RpcError:
                raise ObjectLostError(ref.hex(), "owner unreachable")
            if res.get("data") is not None:
                self.store.put(ref.id, res["data"], ref.owner_id)
                return res["data"]
            if res.get("location"):
                data = self._fetch_from_holder(res["location"], ref)
                if data is not None:
                    return data
            # pending: loop

    def _fetch_from_holder(self, holder_hex: str, ref: ObjectRef) -> bytes | None:
        addr = self._resolve_worker_addr(holder_hex)
        if addr is None:
            return None
        try:
            res = self._peer(addr).call("get_object", oid=ref.hex(), timeout=15)
        except RpcError:
            return None
        if res.get("data") is not None:
            return res["data"]
        return None

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready, pending = [], list(refs)
        while len(ready) < num_returns:
            still = []
            for r in pending:
                if self._local_contains(r.id) or r.id in self._locations:
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        return ready, pending

    # ------------------------------------------------------------------ tasks
    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        from ray_tpu.core.events import global_event_buffer

        return_ids = spec.return_ids()
        for oid in return_ids:
            self.refs.add_owned(oid, self.worker_id, lineage_task=spec.task_id)
        spec.owner_id = self.worker_id
        global_event_buffer().record(
            spec.task_id.hex(), spec.name, "SUBMITTED",
            worker_id=self.worker_id.hex(), job_id=spec.job_id.hex())
        blob = cloudpickle.dumps(spec)
        t = threading.Thread(
            target=self._submit_and_collect, args=(spec, blob, return_ids),
            daemon=True, name=f"submit-{spec.name[:20]}",
        )
        t.start()
        return [ObjectRef(oid, self.worker_id) for oid in return_ids]

    def _submit_and_collect(self, spec: TaskSpec, blob: bytes,
                            return_ids: list[ObjectID]) -> None:
        attempts = 0
        while True:
            try:
                worker = self._acquire_lease(spec)
                try:
                    reply = self._io.run(
                        worker.client.call("push_task", spec_blob=blob, timeout=None)
                    )
                finally:
                    self._release_lease(spec, worker)
                self._handle_task_reply(spec, return_ids, reply)
                return
            except (RpcError, OSError) as e:
                # Worker/daemon failure: retry (system retries, reference
                # semantics: max_retries counts system failures).
                attempts += 1
                if attempts > max(spec.max_retries, 0):
                    self._store_error_local(
                        return_ids, TaskError(RuntimeError(f"system failure: {e}"),
                                              task_desc=spec.name))
                    return
                time.sleep(get_config().task_retry_delay_s)
            except Exception as e:  # noqa: BLE001
                self._store_error_local(return_ids, TaskError(e, task_desc=spec.name))
                return

    def _handle_task_reply(self, spec, return_ids, reply: dict):
        results = reply.get("results", [])
        for oid, r in zip(return_ids, results):
            if r.get("data") is not None:
                self.store.put(oid, r["data"], self.worker_id)
            elif r.get("location"):
                self._locations[oid] = r["location"]

    def _store_error_local(self, return_ids, err):
        blob = serialization.serialize(err)
        for oid in return_ids:
            self.store.put(oid, blob, self.worker_id)

    def _acquire_lease(self, spec: TaskSpec) -> _LeasedWorker:
        key = spec.scheduling_key()
        with self._lease_lock:
            pool = self._leases.setdefault(key, [])
            usable = [w for w in pool if w.inflight < self.MAX_INFLIGHT_PER_WORKER]
            if usable:
                w = min(usable, key=lambda w: w.inflight)
                w.inflight += 1
                return w
        # Need a new lease from a node daemon (local first, follow spillback).
        daemon = self._daemon
        if daemon is None:
            raise RuntimeError("no node daemon attached to this process")
        env_hash = key[1]  # canonical runtime_env JSON from the scheduling key
        res = daemon.call("request_lease", resources=spec.resources,
                          env_hash=env_hash, timeout=None)
        hops = 0
        while res.get("spill") and hops < 4:
            daemon = self._peer(tuple(res["spill"]))
            # Final hop commits to its node: prevents spill ping-pong when
            # every node is briefly busy.
            res = daemon.call("request_lease", resources=spec.resources,
                              env_hash=env_hash, timeout=None,
                              allow_spill=hops < 3)
            hops += 1
        if res.get("spill"):
            # Defensive: the final hop runs with allow_spill=False, and the
            # daemon protocol never returns a spill on that path today. Guard
            # anyway so a future daemon change surfaces as a scheduling error
            # here instead of a KeyError on the missing grant below.
            raise ValueError(
                f"lease spill chain exhausted for {spec.resources}")
        if res.get("error"):
            raise ValueError(res["error"])
        client = AsyncRpcClient(*tuple(res["addr"]))
        self._io.run(client.connect())
        w = _LeasedWorker(res["lease_id"], res["worker_id"], tuple(res["addr"]), client)
        w._daemon = daemon  # remember grantor for return
        w.inflight = 1
        with self._lease_lock:
            self._leases.setdefault(key, []).append(w)
        return w

    def _release_lease(self, spec: TaskSpec, w: _LeasedWorker):
        with self._lease_lock:
            w.inflight -= 1
            if w.inflight <= 0:
                # Leave the lease cached for back-to-back reuse; the reaper
                # returns it (freeing the worker's resources node-side) after
                # the keepalive window (reference: leased workers are returned
                # when idle so other scheduling keys aren't starved).
                w.idle_since = time.monotonic()

    def _lease_reaper(self):
        keepalive = get_config().lease_keepalive_s
        while not self._shutdown:
            time.sleep(keepalive / 2)
            now = time.monotonic()
            to_return: list[_LeasedWorker] = []
            with self._lease_lock:
                for key, pool in list(self._leases.items()):
                    keep = []
                    for w in pool:
                        if w.inflight <= 0 and now - w.idle_since > keepalive:
                            to_return.append(w)
                        else:
                            keep.append(w)
                    if keep:
                        self._leases[key] = keep
                    else:
                        self._leases.pop(key, None)
            for w in to_return:
                try:
                    getattr(w, "_daemon", self._daemon).call(
                        "return_lease", lease_id=w.lease_id)
                except Exception:
                    pass

    def cancel(self, ref: ObjectRef) -> None:
        self._cancelled.add(ref.id)
        self._store_error_local([ref.id], TaskCancelledError())

    # ------------------------------------------------------------------ actors
    def create_actor(self, spec: ActorCreationSpec) -> None:
        spec.owner_id = self.worker_id
        strategy = spec.scheduling_strategy
        res = self.head.call(
            "register_actor",
            actor_id=spec.actor_id.hex(),
            spec_blob=cloudpickle.dumps(spec),
            resources=spec.resources,
            name=spec.name,
            namespace=spec.namespace,
            max_restarts=spec.max_restarts,
            lifetime=spec.lifetime,
            node_affinity=strategy.node_id_hex if strategy.kind == "NODE_AFFINITY" else None,
        )
        if not res.get("ok"):
            raise ValueError(res.get("error", "actor registration failed"))

    def _actor_addr(self, actor_id: ActorID, timeout: float = 60.0) -> tuple[str, int]:
        aid = actor_id.hex()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            addr = self._actor_addr_cache.get(aid)
            if addr:
                return addr
            info = self.head.call("get_actor_info", actor_id=aid)
            if info is None:
                raise ActorDiedError(aid, "unknown actor")
            if info["state"] == "ALIVE" and info["addr"]:
                self._actor_addr_cache[aid] = tuple(info["addr"])
                return tuple(info["addr"])
            if info["state"] == "DEAD":
                raise ActorDiedError(aid, info.get("reason", ""))
            time.sleep(0.02)
        raise ActorDiedError(aid, "timed out waiting for actor to start")

    def submit_actor_task(self, spec: TaskSpec) -> list[ObjectRef]:
        return_ids = spec.return_ids()
        for oid in return_ids:
            self.refs.add_owned(oid, self.worker_id, lineage_task=spec.task_id)
        spec.owner_id = self.worker_id
        blob = cloudpickle.dumps(spec)
        # Ordered per-actor dispatch (reference: sequential_actor_submit_queue
        # orders calls by sequence number; one FIFO dispatcher per actor here
        # preserves program order while pipelining over a single connection).
        with self._actor_queue_lock:
            q = self._actor_queues.get(spec.actor_id.hex())
            if q is None:
                import queue as _q

                q = _q.Queue()
                self._actor_queues[spec.actor_id.hex()] = q
                threading.Thread(
                    target=self._actor_dispatcher, args=(spec.actor_id, q),
                    daemon=True, name=f"adisp-{spec.actor_id.hex()[:8]}",
                ).start()
        q.put((spec, blob, return_ids))
        return [ObjectRef(oid, self.worker_id) for oid in return_ids]

    def _actor_dispatcher(self, actor_id: ActorID, q) -> None:
        # Pipelined ordered dispatch: sends ride one connection in FIFO order;
        # a bounded in-flight window keeps memory in check. Completions are
        # handled on the io loop; failures fall back to the blocking
        # retry/restart path.
        window = threading.Semaphore(128)

        def on_done(spec, blob, return_ids, fut):
            window.release()
            try:
                reply = fut.result()
                if reply.get("dead"):
                    raise RpcError(reply.get("reason", "actor dead"))
                self._handle_task_reply(spec, return_ids, reply)
            except Exception:  # noqa: BLE001
                threading.Thread(
                    target=self._submit_actor_and_collect,
                    args=(spec, blob, return_ids), daemon=True,
                ).start()

        while not self._shutdown:
            item = q.get()
            if item is None:
                return
            spec, blob, return_ids = item
            try:
                addr = self._actor_addr(spec.actor_id)
            except Exception:
                self._submit_actor_and_collect(spec, blob, return_ids)
                continue
            window.acquire()
            client = self._peer(addr)
            cfut = self._io.spawn(
                client.aio.call("push_actor_task", spec_blob=blob, timeout=None)
            )
            cfut.add_done_callback(
                lambda f, s=spec, b=blob, r=return_ids: on_done(s, b, r, f)
            )

    def _submit_actor_and_collect(self, spec, blob, return_ids):
        aid = spec.actor_id.hex()
        attempts = 0
        try:
            while True:
                try:
                    addr = self._actor_addr(spec.actor_id)
                    reply = self._peer(addr).call("push_actor_task", spec_blob=blob,
                                                  timeout=None)
                    if reply.get("dead"):
                        raise ActorDiedError(aid, reply.get("reason", ""))
                    self._handle_task_reply(spec, return_ids, reply)
                    return
                except (RpcError, OSError):
                    # Worker vanished mid-call. If the head says RESTARTING the
                    # call is retried against the new incarnation (reference:
                    # actor_task_submitter retries per max_task_retries while
                    # the GCS FSM restarts the actor).
                    self._actor_addr_cache.pop(aid, None)
                    attempts += 1
                    if attempts > 60:
                        raise ActorDiedError(aid, "worker connection lost")
                    deadline = time.monotonic() + 10.0
                    while time.monotonic() < deadline:
                        try:
                            info = self.head.call("get_actor_info", actor_id=aid)
                        except Exception:
                            info = None
                        state = (info or {}).get("state")
                        if state == "DEAD":
                            raise ActorDiedError(aid, (info or {}).get("reason",
                                                 "worker connection lost"))
                        if state == "ALIVE" and info.get("addr") and \
                                tuple(info["addr"]) != tuple(addr):
                            break  # new incarnation up: retry
                        time.sleep(0.1)
                    else:
                        raise ActorDiedError(aid, "worker connection lost")
        except ActorDiedError as e:
            self._store_error_local(return_ids, e)
        except Exception as e:  # noqa: BLE001
            self._store_error_local(return_ids, TaskError(e, task_desc=spec.name))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.head.call("kill_actor", actor_id=actor_id.hex(), no_restart=no_restart)

    def get_named_actor(self, name: str, namespace: str = "default") -> ActorID | None:
        res = self.head.call("get_named_actor", name=name, namespace=namespace)
        return ActorID.from_hex(res["actor_id"]) if res.get("actor_id") else None

    def actor_is_alive(self, actor_id: ActorID) -> bool:
        info = self.head.call("get_actor_info", actor_id=actor_id.hex())
        return bool(info and info["state"] == "ALIVE")

    # ------------------------------------------------------------------ placement groups
    def create_placement_group(self, pg_id, bundles, strategy, name=None,
                               labels=None) -> None:
        self.head.call("create_placement_group", pg_id=pg_id.hex(),
                       bundles=bundles, strategy=strategy, name=name)

    def remove_placement_group(self, pg_id) -> None:
        self.head.call("remove_placement_group", pg_id=pg_id.hex())

    def placement_group_state(self, pg_id) -> str:
        return self.head.call("placement_group_state", pg_id=pg_id.hex())["state"]

    # ------------------------------------------------------------------ KV
    def kv_put(self, key: str, value: bytes, ns: str = "default") -> None:
        self.head.call("kv_put", ns=ns, key=key, value=value)

    def kv_get(self, key: str, ns: str = "default") -> bytes | None:
        return self.head.call("kv_get", ns=ns, key=key).get("value")

    def kv_del(self, key: str, ns: str = "default") -> None:
        self.head.call("kv_del", ns=ns, key=key)

    def kv_keys(self, prefix: str = "", ns: str = "default") -> list[str]:
        return self.head.call("kv_keys", ns=ns, prefix=prefix)["keys"]

    # ------------------------------------------------------------------ misc
    def state_snapshot(self) -> dict:
        snap = self.head.call("state_snapshot")
        snap["objects"] = self.store.stats()
        return snap

    def task_events(self, since: int = 0, epoch: str = "") -> dict:
        """Cluster-wide task events newer than the ``since`` cursor."""
        return self.head.call("get_task_events", since=since, epoch=epoch)

    def cluster_resources(self) -> dict[str, float]:
        return self.head.call("cluster_resources")

    def available_resources(self) -> dict[str, float]:
        return self.head.call("available_resources")

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self._io.run(self.server.stop())
        except Exception:
            pass
        for cli in list(self._peer_clients.values()):
            cli.close()
        self.head.close()
        if self._daemon:
            self._daemon.close()
