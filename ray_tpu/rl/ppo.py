"""PPO: clipped-surrogate policy optimization in pure JAX.

Capability parity with the reference's PPO (reference:
rllib/algorithms/ppo/ppo.py + ppo_learner.py — GAE advantages, clipped
policy loss, value-function loss with clipping, entropy bonus, minibatched
multi-epoch SGD; Algorithm is a Tune Trainable): networks, GAE, and the
update are jit-compiled JAX, so the same Learner runs on CPU for tests and
on TPU meshes for scale. The Algorithm plugs into ray_tpu.tune unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.tune.trainable import Trainable


# ---------------------------------------------------------------------------
# policy / value networks (MLPs)
# ---------------------------------------------------------------------------

def init_mlp(key, sizes, scale_last=0.01):
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = scale_last if i == len(sizes) - 2 else np.sqrt(2.0 / fan_in)
        params.append({
            "w": jax.random.normal(sub, (fan_in, fan_out)) * scale,
            "b": jnp.zeros((fan_out,)),
        })
    return params


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def init_policy(key, obs_size: int, num_actions: int, hidden: int = 64):
    kp, kv = jax.random.split(key)
    return {
        "pi": init_mlp(kp, [obs_size, hidden, hidden, num_actions]),
        "vf": init_mlp(kv, [obs_size, hidden, hidden, 1], scale_last=1.0),
    }


@jax.jit
def _act(params, obs, seed):
    logits = mlp_apply(params["pi"], obs)
    value = mlp_apply(params["vf"], obs)[..., 0]
    key = jax.random.PRNGKey(seed)
    actions = jax.random.categorical(key, logits, axis=-1)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, actions[..., None], axis=-1)[..., 0]
    return actions, logp, value


# ---------------------------------------------------------------------------
# GAE + update
# ---------------------------------------------------------------------------

def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """[T, N] arrays -> (advantages, returns), reverse-scan GAE."""
    T = rewards.shape[0]
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    not_done = 1.0 - dones.astype(jnp.float32)
    deltas = rewards + gamma * next_values * not_done - values

    def scan_fn(carry, t):
        adv = deltas[t] + gamma * lam * not_done[t] * carry
        return adv, adv

    _, advs = jax.lax.scan(scan_fn, jnp.zeros_like(last_values),
                           jnp.arange(T - 1, -1, -1))
    advantages = advs[::-1]
    return advantages, advantages + values


# Host-side callers (legacy PPO step, the Sebulba learner) go through the
# jitted entry: the reverse scan traced eagerly costs ~0.5 ms/step in op
# dispatch, which at unroll 64+ dominates the whole update. Anakin calls
# the raw function from inside its own fused program.
compute_gae_jit = jax.jit(compute_gae, static_argnums=(4, 5))


@partial(jax.jit, static_argnums=(0, 1))
def ppo_update(optimizer, cfg_static, params, opt_state, batch, seed):
    """One epoch set of minibatched clipped-PPO updates.

    batch: flat [B, ...] arrays (obs, actions, logp, advantages, returns).
    cfg_static: (clip, vf_coef, ent_coef, num_minibatches, epochs).
    """
    clip, vf_coef, ent_coef, num_mb, epochs = cfg_static
    B = batch["obs"].shape[0]
    mb = B // num_mb

    def loss_fn(p, mb_batch):
        logits = mlp_apply(p["pi"], mb_batch["obs"])
        values = mlp_apply(p["vf"], mb_batch["obs"])[..., 0]
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, mb_batch["actions"][..., None], axis=-1)[..., 0]
        ratio = jnp.exp(logp - mb_batch["logp"])
        adv = mb_batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.minimum(ratio * adv,
                          jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
        vf = 0.5 * ((values - mb_batch["returns"]) ** 2).mean()
        ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        return pg + vf_coef * vf - ent_coef * ent, (pg, vf, ent)

    def mb_step(carry, idx):
        p, os_ = carry
        mb_batch = jax.tree.map(lambda x: x[idx], batch)
        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, mb_batch)
        updates, os_ = optimizer.update(grads, os_, p)
        p = optax.apply_updates(p, updates)
        return (p, os_), aux

    def epoch(carry, key):
        perm = jax.random.permutation(key, B)
        idxs = perm[: num_mb * mb].reshape(num_mb, mb)
        return jax.lax.scan(mb_step, carry, idxs)

    keys = jax.random.split(jax.random.PRNGKey(seed), epochs)
    (params, opt_state), aux = jax.lax.scan(epoch, (params, opt_state), keys)
    pg, vf, ent = jax.tree.map(lambda a: a[-1, -1], aux)
    return params, opt_state, {"policy_loss": pg, "vf_loss": vf,
                               "entropy": ent}


# ---------------------------------------------------------------------------
# Algorithm (a Tune Trainable — reference: Algorithm(Trainable))
# ---------------------------------------------------------------------------

@dataclass
class PPOConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 0          # 0 = inline rollouts
    num_envs_per_runner: int = 8
    rollout_len: int = 128
    # --- Podracer fast paths (rl/anakin.py, rl/sebulba.py) -------------
    # vectorized=True routes envs with a JAX implementation (rl/vec_env)
    # to the fused Anakin program (num_env_runners == 0) or the Sebulba
    # streaming actors (num_env_runners > 0); Python-only envs fall back
    # to the EnvRunnerGroup path below. Knob registry: utils/config.py
    # ("RL vectorized Podracer paths").
    vectorized: bool = False
    num_envs: int = 0                 # total vectorized envs (0 = derive
    #                                   from num_envs_per_runner x runners)
    unroll_len: int = 0               # scan length (0 = rollout_len)
    sebulba_staleness: int = 2        # drop blocks older than this many
    #                                   weight versions
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip: float = 0.2
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    num_minibatches: int = 4
    num_epochs: int = 4
    hidden: int = 64
    seed: int = 0
    # () -> (env_to_module, module_to_env) connector pipelines, built per
    # runner (reference: rllib/connectors/ — see rl/connectors.py).
    connector_factory: Any = None
    extra: dict = field(default_factory=dict)

    def build(self) -> "PPO":
        return PPO({"ppo_config": self})


class PPO(Trainable):
    """EnvRunnerGroup sampling + JAX learner update per step(); usable
    standalone or under ray_tpu.tune.Tuner (reference: algorithm.py:212)."""

    def setup(self, config: dict) -> None:
        cfg = config.get("ppo_config") or PPOConfig(
            **{k: v for k, v in config.items() if k in PPOConfig.__dataclass_fields__})
        self.cfg = cfg
        # Podracer dispatch: vectorized + JAX env -> fused Anakin program
        # (colocated) or Sebulba streaming actors (distributed); anything
        # else keeps the EnvRunnerGroup path as the fallback.
        self._engine = None
        if cfg.vectorized:
            from ray_tpu.rl.vec_env import is_jax_env

            if is_jax_env(cfg.env):
                if cfg.num_env_runners > 0:
                    from ray_tpu.rl.sebulba import SebulbaPPO

                    self._engine = SebulbaPPO(cfg)
                else:
                    from ray_tpu.rl.anakin import AnakinPPO

                    self._engine = AnakinPPO(cfg)
                return
        probe = make_env(cfg.env, seed=cfg.seed)
        obs_size, num_actions = probe.observation_size, probe.num_actions
        if cfg.connector_factory is not None:
            # Frame stacking etc. widen the policy's observation input.
            e2m_probe, _ = cfg.connector_factory()
            obs_size *= getattr(e2m_probe, "output_multiplier", 1)
        self.params = init_policy(jax.random.PRNGKey(cfg.seed), obs_size,
                                  num_actions, cfg.hidden)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)

        def policy_factory(params=None):
            def act(p, obs, seed):
                a, lp, v = _act(p, jnp.asarray(obs), seed)
                return np.asarray(a), np.asarray(lp), np.asarray(v)
            return act, None  # weights pushed via set_weights

        self.runners = EnvRunnerGroup(
            cfg.env, num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_runner,
            rollout_len=cfg.rollout_len, policy_factory=policy_factory,
            seed=cfg.seed, connector_factory=cfg.connector_factory)
        self._return_window: list[float] = []

    def step(self) -> dict:
        if self._engine is not None:
            return self._engine.step()
        cfg = self.cfg
        samples = self.runners.sample(self.params)
        advs, rets, flats = [], [], []
        for s in samples:
            adv, ret = compute_gae_jit(
                jnp.asarray(s["rewards"]), jnp.asarray(s["values"]),
                jnp.asarray(s["dones"]), jnp.asarray(s["last_values"]),
                cfg.gamma, cfg.gae_lambda)
            flats.append({
                "obs": s["obs"].reshape(-1, s["obs"].shape[-1]),
                "actions": s["actions"].reshape(-1),
                "logp": s["logp"].reshape(-1),
                "advantages": np.asarray(adv).reshape(-1),
                "returns": np.asarray(ret).reshape(-1),
            })
            self._return_window.extend(s["episode_returns"])
        batch = {k: jnp.asarray(np.concatenate([f[k] for f in flats]))
                 for k in flats[0]}
        static = (cfg.clip, cfg.vf_coef, cfg.ent_coef, cfg.num_minibatches,
                  cfg.num_epochs)
        self.params, self.opt_state, stats = ppo_update(
            self.optimizer, static, self.params, self.opt_state, batch,
            cfg.seed + self.iteration)
        self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else 0.0)
        return {
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled": int(batch["obs"].shape[0]),
            **{k: float(v) for k, v in stats.items()},
        }

    def save_checkpoint(self) -> Any:
        if self._engine is not None:
            return {"params": self._engine.host_params(),
                    "iteration": self.iteration, "connector_state": {}}
        return {"params": jax.tree.map(np.asarray, self.params),
                "iteration": self.iteration,
                # A policy trained behind a running normalizer is only
                # meaningful WITH that normalizer's statistics.
                "connector_state": self.runners.connector_state()}

    def load_checkpoint(self, checkpoint: Any) -> None:
        self.iteration = checkpoint["iteration"]
        if self._engine is not None:
            self._engine.set_params(checkpoint["params"])
            return
        self.params = jax.tree.map(jnp.asarray, checkpoint["params"])
        self.runners.set_connector_state(
            checkpoint.get("connector_state", {}))

    def cleanup(self) -> None:
        if self._engine is not None:
            shutdown = getattr(self._engine, "shutdown", None)
            if shutdown is not None:
                shutdown()
            return
        self.runners.shutdown()
