"""Channels: the zero-RPC-scheduling data plane of compiled graphs.

Capability parity with the reference's channel layer (reference:
python/ray/experimental/channel/ — shared_memory_channel.py mutable-object
channels backed by C++ experimental_mutable_object_manager.cc,
intra_process_channel.py for same-process readers): a channel is a named
single-writer multi-reader slot carrying one value per execution step.

Three transports:
- ``LocalChannel``: same-process queues (threaded local runtime). Pickling
  transfers only the name; deserialization re-attaches to the process-global
  registry, so actor threads and the driver share one instance.
- ``StoreChannel``: versioned slots in the cluster KV. Works across any two
  processes on any nodes; data moves without task scheduling but pays a KV
  round-trip per hop — kept as the fallback/baseline transport (select with
  the ``dag_channel="kv"`` knob).
- ``direct.DirectChannel`` (ray_tpu/dag/direct.py): the cluster default —
  peer-to-peer push frames with store-backed buffers for large payloads;
  the head is consulted once at compile time for route exchange, never per
  step (reference cross-node channels similarly push mutable objects
  raylet-to-raylet, node_manager.cc:748 HandlePushMutableObject).
"""

from __future__ import annotations

import queue
import time
from typing import Any

from ray_tpu.utils import serialization


class ChannelClosed(Exception):
    pass


_CLOSE = b"__rtpu_channel_closed__"

_local_registry: dict[str, "LocalChannel"] = {}


def _lookup_local_channel(name: str) -> "LocalChannel":
    chan = _local_registry.get(name)
    if chan is None:
        raise RuntimeError(f"local channel {name!r} not in this process")
    return chan


class LocalChannel:
    """Same-process channel: one bounded queue per reader."""

    def __init__(self, name: str, num_readers: int = 1,
                 maxsize: int | None = None):
        if maxsize is None:
            from ray_tpu.utils.config import get_config

            maxsize = get_config().dag_channel_capacity
        self.name = name
        self._queues = [queue.Queue(maxsize=maxsize) for _ in range(num_readers)]
        self._closed = False
        _local_registry[name] = self

    def __reduce__(self):
        # Same-process identity: actors receive the registry instance, not a
        # copy (a copied queue would never see the driver's writes).
        return (_lookup_local_channel, (self.name,))

    def write(self, value: Any) -> None:
        if self._closed:
            raise ChannelClosed(self.name)
        for q in self._queues:
            q.put(value)

    def read(self, reader_index: int = 0, timeout: float | None = None) -> Any:
        try:
            value = self._queues[reader_index].get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"channel {self.name}") from None
        if isinstance(value, bytes) and value == _CLOSE:
            # Propagate to any other blocked reader of the same queue set.
            self._queues[reader_index].put(_CLOSE)
            raise ChannelClosed(self.name)
        return value

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for q in self._queues:
            q.put(_CLOSE)

    def destroy(self) -> None:
        """Drop the registry entry (teardown) so queues can be collected."""
        self.close()
        _local_registry.pop(self.name, None)

    def connect(self, runtime) -> "LocalChannel":
        return self


class StoreChannel:
    """Cross-process channel over the cluster KV.

    Single writer; each reader holds a private cursor. Slots are keyed
    ``(name, seq)``; single-reader channels delete a slot on consumption,
    multi-reader slots are reclaimed at close() (readers poll with backoff —
    the reference blocks on a mutable-object futex; polling is the portable
    equivalent).
    """

    def __init__(self, name: str, num_readers: int = 1):
        self.name = name
        self.num_readers = num_readers
        self._write_seq = 0
        # One cursor per reader index: a single pickled instance can serve
        # several read sites of one process (distinct reader_index each).
        self._read_seq: dict[int, int] = {}
        # Last PUBLISHED cursor per reader index: publishes are batched to
        # one kv_put per _GC_EVERY reads (flushed when the close marker is
        # observed), so multi-reader consumption stops costing one head RPC
        # per read.
        self._cursor_pub: dict[int, int] = {}
        self._runtime = None

    # Pickled into actors: only the identity travels; cursors and the runtime
    # binding are per-process.
    def __getstate__(self):
        return {"name": self.name, "num_readers": self.num_readers}

    def __setstate__(self, state):
        self.name = state["name"]
        self.num_readers = state["num_readers"]
        self._write_seq = 0
        self._read_seq = {}
        self._cursor_pub = {}
        self._runtime = None

    def connect(self, runtime) -> "StoreChannel":
        if self._runtime is None:
            self._runtime = runtime
        return self

    def _key(self, seq: int) -> str:
        return f"chan/{self.name}/{seq}"

    def _write_raw(self, blob: bytes) -> None:
        self._runtime.kv_put(self._key(self._write_seq), blob, ns="channels")
        self._write_seq += 1

    _GC_EVERY = 16  # writer reclaims consumed multi-reader slots this often

    def _cursor_key(self, reader_index: int) -> str:
        return f"chancur/{self.name}/{reader_index}"

    def read(self, reader_index: int = 0, timeout: float | None = None) -> Any:
        assert self._runtime is not None, "channel not connected"
        seq = self._read_seq.get(reader_index, 0)
        key = self._key(seq)
        deadline = None if timeout is None else time.monotonic() + timeout
        sleep = 0.0005
        while True:
            blob = self._runtime.kv_get(key, ns="channels")
            if blob is not None:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} seq {seq}")
            time.sleep(sleep)
            sleep = min(sleep * 2, 0.01)
        if bytes(blob) == _CLOSE:
            # Cursor stays on the marker: every subsequent read re-raises
            # immediately instead of polling a seq that will never arrive.
            # Flush the batched cursor so the writer can reclaim everything
            # this reader consumed before the marker.
            self._flush_cursor(reader_index)
            raise ChannelClosed(self.name)
        self._read_seq[reader_index] = seq + 1
        value = serialization.deserialize(blob)
        if self.num_readers == 1:
            self._runtime.kv_del(key, ns="channels")
        elif (seq + 1) % self._GC_EVERY == 0:
            # Batched cursor publish: one kv_put per _GC_EVERY reads (not
            # per read) tells the writer which slots every reader passed.
            self._flush_cursor(reader_index)
        return value

    def _flush_cursor(self, reader_index: int) -> None:
        cur = self._read_seq.get(reader_index, 0)
        if self.num_readers > 1 and cur > self._cursor_pub.get(reader_index, 0):
            self._runtime.kv_put(self._cursor_key(reader_index),
                                 str(cur).encode(), ns="channels")
            self._cursor_pub[reader_index] = cur

    def _gc(self) -> None:
        cursors = []
        for i in range(self.num_readers):
            raw = self._runtime.kv_get(self._cursor_key(i), ns="channels")
            cursors.append(int(raw) if raw else 0)
        low = min(cursors)
        for seq in range(getattr(self, "_gc_floor", 0), low):
            self._runtime.kv_del(self._key(seq), ns="channels")
        self._gc_floor = low

    def write(self, value: Any) -> None:
        assert self._runtime is not None, "channel not connected"
        blob = serialization.serialize(value)
        self._runtime.kv_put(self._key(self._write_seq), blob, ns="channels")
        self._write_seq += 1
        if self.num_readers > 1 and self._write_seq % self._GC_EVERY == 0:
            self._gc()

    def close(self) -> None:
        # Only append the close marker: lagging readers must still drain the
        # slots before their cursor (they GC themselves / via writer GC).
        assert self._runtime is not None, "channel not connected"
        self._write_raw(_CLOSE)

    def destroy(self) -> None:
        """Remove every slot and cursor key (teardown, after loops exited)."""
        assert self._runtime is not None, "channel not connected"
        for ns_prefix in (f"chan/{self.name}/", f"chancur/{self.name}/"):
            for key in self._runtime.kv_keys(prefix=ns_prefix, ns="channels"):
                self._runtime.kv_del(key, ns="channels")


class _DeviceArrayEnvelope:
    """Out-of-band marker for device arrays in transit. A private class
    (not an in-band tuple sentinel) so no user value can ever be mistaken
    for an encoded array — pattern-matching user data corrupts payloads."""

    __slots__ = ("raw", "shape", "dtype")

    def __init__(self, raw: bytes, shape, dtype: str):
        self.raw = raw
        self.shape = shape
        self.dtype = dtype

    def __reduce__(self):
        return (_DeviceArrayEnvelope, (self.raw, self.shape, self.dtype))


class DeviceChannel:
    """Device-array channel: jax.Array values cross the wire as raw
    host bytes + aval and land back ON DEVICE at the reader via
    jax.device_put (reference: the accelerator channels of
    experimental/channel/ — torch_tensor_accelerator_channel.py moves
    tensors through the device transport registered in
    accelerator_context.py:222; here the transport is jax host transfer,
    with ICI send/recv available through the registered communicator for
    in-mesh collectives).

    Wraps any inner channel (Local or Store) for the control/bytes path.
    Non-array values pass through unchanged, so mixed schedules work.
    """

    def __init__(self, inner):
        self.inner = inner
        self.name = inner.name

    def connect(self, runtime) -> "DeviceChannel":
        self.inner.connect(runtime)
        return self

    def ensure_reader(self, reader_index: int = 0) -> None:
        # Route publication passthrough for direct inner channels.
        if hasattr(self.inner, "ensure_reader"):
            self.inner.ensure_reader(reader_index)

    def write(self, value: Any) -> None:
        try:
            import jax
            import numpy as np

            if isinstance(value, jax.Array):
                host = np.asarray(value)
                self.inner.write(_DeviceArrayEnvelope(
                    host.tobytes(), host.shape, str(host.dtype)))
                return
        except ImportError:
            pass
        self.inner.write(value)

    def read(self, reader_index: int = 0, timeout: float | None = None) -> Any:
        value = self.inner.read(reader_index, timeout=timeout)
        if isinstance(value, _DeviceArrayEnvelope):
            import jax
            import numpy as np

            return jax.device_put(
                np.frombuffer(value.raw, dtype=value.dtype)
                .reshape(value.shape))
        return value

    def close(self) -> None:
        self.inner.close()

    def destroy(self) -> None:
        self.inner.destroy()
