"""RMSNorm: Pallas fused kernel + reference implementation.

The TPU framework owns its normalization kernels (the reference delegates to
torch). RMSNorm (no mean subtraction) is the transformer default (Llama-family).
The Pallas kernel fuses the reduction, rsqrt, and scale multiply in VMEM; the
jnp path is used off-TPU and for autodiff (XLA fuses it into neighbors anyway
— the kernel exists for the cases XLA's fusion boundary splits, e.g. ahead of
a sharded matmul).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def rms_norm_reference(x, weight, eps: float = 1e-6):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def rms_norm_pallas(x, weight, eps: float = 1e-6, block_rows: int = 256):
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    d = orig_shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    if rows % block_rows != 0:
        return rms_norm_reference(x, weight, eps)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
    )(x2, weight)
    return out.reshape(orig_shape)


def rms_norm(x, weight, eps: float = 1e-6):
    """Dispatch: Pallas on TPU forward, reference elsewhere (and for grad —
    custom_vjp recomputes via the reference path)."""
    if jax.default_backend() == "tpu":
        return _rms_norm_cv(x, weight, eps)
    return rms_norm_reference(x, weight, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_cv(x, weight, eps):
    return rms_norm_pallas(x, weight, eps)


def _rms_fwd(x, weight, eps):
    return rms_norm_pallas(x, weight, eps), (x, weight)


def _rms_bwd(eps, res, g):
    x, weight = res
    _, vjp = jax.vjp(lambda x_, w_: rms_norm_reference(x_, w_, eps), x, weight)
    return vjp(g)


_rms_norm_cv.defvjp(_rms_fwd, _rms_bwd)
