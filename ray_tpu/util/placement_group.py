"""Placement groups: atomic multi-bundle resource reservation.

Capability parity with the reference (reference:
python/ray/util/placement_group.py — placement_group() :126, PlacementGroup
handle :22; GCS-side 2PC in gcs_placement_group_scheduler.h CommitAllBundles
:425 with raylet prepare/commit at node_manager.cc:1896/1913; bundle
strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD from
bundle_scheduling_policy.h:85-109).

Mechanism: committed bundles materialize as derived node resources named
``{res}_pg_{id}_{bundle}`` (the reference uses the same trick with
CPU_group_* resources); tasks/actors scheduled with a
PlacementGroupSchedulingStrategy have their demands rewritten onto those
derived resources, so the normal lease scheduler enforces reservation.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ray_tpu.core.exceptions import PlacementGroupSchedulingError
from ray_tpu.core.task_spec import SchedulingStrategy
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: list[dict[str, float]]
    strategy: str = "PACK"
    # Creation-reply hint: the head inlines the first placement attempt, so
    # a PG born CREATED lets the first ready() answer without a state RPC
    # (consumed once — later calls re-poll, observing removals).
    created_hint: bool = False

    def ready(self, timeout: float | None = 60.0) -> bool:
        if self.created_hint:
            self.created_hint = False
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        sleep = 0.001  # adaptive: sub-ms-fresh PGs resolve on early polls
        while True:
            state = global_worker.runtime.placement_group_state(self.id)
            if state == "CREATED":
                return True
            if state in ("REMOVED", "FAILED"):
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(sleep)
            sleep = min(sleep * 2, 0.02)

    def wait(self, timeout: float | None = 60.0) -> bool:
        return self.ready(timeout)

    def bundle_resource_name(self, res: str, bundle_index: int) -> str:
        return f"{res}_pg_{self.id.hex()[:16]}_{bundle_index}"


def placement_group(bundles: list[dict[str, float]], strategy: str = "PACK",
                    name: str | None = None,
                    labels: dict[str, str] | None = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    global_worker.check_connected()
    pg_id = PlacementGroupID.from_random()
    state = global_worker.runtime.create_placement_group(
        pg_id, [dict(b) for b in bundles], strategy, name, labels)
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy,
                          created_hint=state == "CREATED")


def remove_placement_group(pg: PlacementGroup) -> None:
    global_worker.runtime.remove_placement_group(pg.id)


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: PlacementGroup
    placement_group_bundle_index: int = 0

    def to_scheduling_strategy(self) -> SchedulingStrategy:
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id_hex=self.placement_group.id.hex(),
            bundle_index=self.placement_group_bundle_index,
        )


def rewrite_resources_for_pg(resources: dict[str, float],
                             strategy) -> dict[str, float]:
    """Map a demand onto a bundle's derived resources."""
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        idx = strategy.placement_group_bundle_index
        if idx >= len(pg.bundles):
            raise PlacementGroupSchedulingError(
                f"bundle index {idx} out of range ({len(pg.bundles)} bundles)")
        bundle = pg.bundles[idx]
        for k, v in resources.items():
            if v > bundle.get(k, 0.0):
                raise PlacementGroupSchedulingError(
                    f"demand {{{k}: {v}}} exceeds bundle {idx} ({bundle}); "
                    "the task would never be schedulable")
        out = {pg.bundle_resource_name(k, idx): v
               for k, v in resources.items()}
        # Marker pins even zero-resource tasks to the bundle's node
        # (reference: bundle_group_* 0.001-resource trick).
        out[f"bundle_pg_{pg.id.hex()[:16]}_{idx}"] = 0.001
        return out
    return resources
