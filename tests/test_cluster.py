"""Distributed runtime: multiprocess tasks/actors across real process
boundaries, node membership, failure handling.

Coverage modeled on the reference's cluster fixtures + chaos shapes
(reference: python/ray/tests/conftest.py ray_start_cluster :647;
test_utils.py ResourceKillerActor :1279 for kill-based fault injection).
The head + node daemons run in-process (1-core box); workers are real
subprocesses.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.worker import global_worker
from ray_tpu.utils.ids import JobID


from _test_util import load_factor as _load_factor


@pytest.fixture(scope="module")
def cluster():
    os.environ["RTPU_WORKER_IDLE_TTL_S"] = "120"
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster()
    c.add_node(num_cpus=4, resources={"TPU": 4.0}, labels={"zone": "a"})
    rt = c.connect()
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    yield c
    rt.shutdown()
    c.shutdown()
    global_worker.runtime = None
    config_mod.set_config(config_mod.Config.load())


def test_task_crosses_process_boundary(cluster):
    @remote
    def whoami():
        return os.getpid()

    pid = ray_tpu.get(whoami.remote(), timeout=60)
    assert pid != os.getpid()


def test_task_args_and_refs(cluster):
    @remote
    def add(a, b):
        return a + b

    ref = ray_tpu.put(10)
    assert ray_tpu.get(add.remote(ref, 5), timeout=60) == 15


def test_parallel_tasks_reuse_lease(cluster):
    @remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs, timeout=60) == [i * i for i in range(20)]


def test_large_object_location_fetch(cluster):
    import numpy as np

    @remote
    def big():
        return np.ones(300_000, dtype=np.float32)  # > inline threshold

    arr = ray_tpu.get(big.remote(), timeout=60)
    assert arr.shape == (300_000,)
    assert float(arr[0]) == 1.0


def test_shm_arena_carries_large_objects(cluster):
    """Large results/puts ride the node's native shm arena (zero-copy
    intra-node path) when the native store built."""
    import numpy as np

    rt = global_worker.runtime
    if rt.shm is None:
        pytest.skip("native shm store unavailable")
    before = rt.shm.stats()["num_objects"]

    ref = ray_tpu.put(np.arange(200_000, dtype=np.float32))
    assert rt.shm.stats()["num_objects"] == before + 1

    @remote
    def consume(a):
        return float(a.sum())

    total = ray_tpu.get(consume.remote(ref), timeout=60)
    assert total == float(np.arange(200_000, dtype=np.float32).sum())

    @remote
    def produce():
        return np.full(150_000, 2.0, dtype=np.float32)

    out_ref = produce.remote()  # keep the ref alive: GC deletes on release
    out = ray_tpu.get(out_ref, timeout=60)
    assert float(out[0]) == 2.0
    # The worker deposited its large result into the shared arena.
    assert rt.shm.stats()["num_objects"] >= before + 2

    # And releasing the refs GCs the arena entries (owner-driven delete).
    # Load-factor-scaled window: the release -> owner -> daemon delete
    # chain rides background RPC ticks that stretch under residual suite
    # load (PR-8 measured a fixed 10s window missing 3/10 on a loaded
    # box — the GC always lands, just late).
    del ref, out_ref
    deadline = time.monotonic() + 10 * _load_factor()
    while time.monotonic() < deadline and \
            rt.shm.stats()["num_objects"] > before:
        time.sleep(0.05)
    assert rt.shm.stats()["num_objects"] == before


def test_task_error_remote_traceback(cluster):
    @remote
    def boom():
        raise ValueError("cluster kaboom")

    with pytest.raises(ray_tpu.TaskError) as ei:
        ray_tpu.get(boom.remote(), timeout=60)
    assert "cluster kaboom" in str(ei.value)


def test_nested_task_submission(cluster):
    @remote
    def inner(x):
        return x + 1

    @remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1), timeout=60) == 12


def test_actor_lifecycle(cluster):
    @remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="c1").remote(0)
    assert ray_tpu.get([c.inc.remote() for _ in range(5)], timeout=60) == [1, 2, 3, 4, 5]
    h = ray_tpu.get_actor("c1")
    assert ray_tpu.get(h.inc.remote(), timeout=30) == 6
    ray_tpu.kill(c)
    time.sleep(0.5)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_restart_on_worker_crash(cluster):
    @remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def count(self):
            self.calls += 1
            return self.calls

        def die(self):
            os._exit(1)

    p = Phoenix.options(name="phx").remote()
    assert ray_tpu.get(p.count.remote(), timeout=60) == 1
    p.die.remote()  # kills the worker process
    time.sleep(1.0)
    # restarted incarnation: state reset, calls work again (generous
    # deadline: a restart forks + imports a fresh worker, which contends
    # with the whole suite on a 1-core box)
    deadline = time.monotonic() + 90
    val = None
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(p.count.remote(), timeout=30)
            break
        except ray_tpu.ActorDiedError:
            time.sleep(0.5)
    assert val == 1  # fresh state after restart


def test_multi_node_spillback(cluster):
    # second node with a resource only it has; task must spill to it
    cluster.add_node(num_cpus=2, resources={"special": 1.0}, labels={"zone": "b"})
    time.sleep(0.3)

    @remote(resources={"special": 1.0})
    def on_special():
        return "spilled"

    assert ray_tpu.get(on_special.remote(), timeout=60) == "spilled"


def test_cluster_resources_aggregate(cluster):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] >= 4.0
    assert total["TPU"] == 4.0


def test_kv_store(cluster):
    rt = global_worker.runtime
    rt.kv_put("k1", b"v1")
    assert rt.kv_get("k1") == b"v1"
    rt.kv_del("k1")
    assert rt.kv_get("k1") is None


def test_node_death_detection(cluster):
    node = cluster.add_node(num_cpus=1, labels={"doomed": "yes"})
    time.sleep(0.3)
    nodes = global_worker.runtime.head.call("list_nodes")
    nid = node.node_id
    assert nodes[nid]["alive"]
    cluster.remove_node(node)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes = global_worker.runtime.head.call("list_nodes")
        if not nodes[nid]["alive"]:
            break
        time.sleep(0.2)
    assert not nodes[nid]["alive"]


def test_cancel_running_task(cluster):
    """A long-running task is interrupted in its worker (reference:
    CoreWorker::CancelTask raises in the executing thread)."""

    @remote
    def spin():
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30:
            time.sleep(0.01)
        return "finished"

    ref = spin.remote()
    time.sleep(1.0)  # let it start executing
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=20)


def test_cancel_queued_task(cluster):
    """A task cancelled while queued behind a busy resource never runs."""

    @remote(resources={"TPU": 4.0})
    def hold(sec):
        time.sleep(sec)
        return "held"

    holder = hold.remote(3.0)
    time.sleep(0.5)  # holder now occupies all 4 TPU
    victim = hold.remote(0.0)  # queued: no TPU available
    ray_tpu.cancel(victim)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(victim, timeout=20)
    assert ray_tpu.get(holder, timeout=20) == "held"


def test_streaming_generator_cross_process(cluster):
    """Streamed items arrive incrementally across the process boundary."""

    @remote(num_returns="streaming")
    def slow_gen(n):
        import time as _t
        for i in range(n):
            _t.sleep(0.05)
            yield i

    t0 = time.monotonic()
    arrivals = []
    for ref in slow_gen.remote(4):
        ray_tpu.get(ref)
        arrivals.append(time.monotonic() - t0)
    # items spaced out, not batched at the end
    assert arrivals[0] < arrivals[-1] - 0.1


def test_streaming_large_items_cross_process(cluster):
    import numpy as np

    @remote(num_returns="streaming")
    def big(n):
        import numpy as np
        for i in range(n):
            yield np.full(200_000, i, np.float32)  # > inline threshold

    out = [ray_tpu.get(r) for r in big.remote(3)]
    assert [int(a[0]) for a in out] == [0, 1, 2]


def test_lineage_reconstruction_on_worker_death(cluster):
    """Kill the worker holding a large task result; get() transparently
    recomputes it by resubmitting the creating task (reference:
    object_recovery_manager.h:41 + lineage in task_manager.h:184)."""
    import numpy as np

    @remote
    def build(seed):
        import numpy as np
        return np.full(300_000, seed, np.float32)  # > inline: stays at holder

    ref = build.remote(7)
    first = ray_tpu.get(ref, timeout=60)
    assert float(first[0]) == 7.0

    # Forget the local borrow-cache copy so the next get must re-fetch,
    # then kill every worker (the holder dies with them).
    rt = global_worker.runtime
    rt.store.delete(ref.id)
    if rt.shm is not None:
        try:
            rt.shm.delete(ref.id.binary())
        except Exception:
            pass
    killed = cluster.kill_workers()
    assert killed >= 1
    time.sleep(0.5)

    again = ray_tpu.get(ref, timeout=120)  # transparent recompute
    assert float(again[0]) == 7.0 and again.shape == (300_000,)


def test_recovery_attempts_not_burned_by_polling(cluster):
    """Getters polling while a recovery is in flight must not consume the
    bounded recovery budget (runtime.py _recover_object dedup-before-count;
    this raced as a spurious ObjectLostError under load)."""
    import numpy as np

    @remote
    def build():
        import numpy as np
        return np.full(300_000, 3.0, np.float32)

    ref = build.remote()
    assert float(ray_tpu.get(ref, timeout=60)[0]) == 3.0
    rt = global_worker.runtime
    rt.store.delete(ref.id)
    if rt.shm is not None:
        try:
            rt.shm.delete(ref.id.binary())
        except Exception:
            pass
    cluster.kill_workers()
    time.sleep(0.3)
    # Hammer the recovery entry point like racing getters would.
    for _ in range(6):
        assert rt._recover_object(ref.id)
    assert rt._recovery_attempts.get(ref.id, 0) <= 1
    again = ray_tpu.get(ref, timeout=120)
    assert float(again[0]) == 3.0


def test_put_objects_are_not_reconstructable(cluster):
    """Lost put() objects raise ObjectLostError (no lineage — reference
    semantics: only task returns reconstruct)."""
    rt = global_worker.runtime
    ref = ray_tpu.put(b"x" * 100_000)
    # Simulate total loss of every stored copy.
    rt.store.delete(ref.id)
    if rt.shm is not None:
        try:
            rt.shm.delete(ref.id.binary())
        except Exception:
            pass
    rt._locations[ref.id] = "00" * 16  # bogus dead holder
    with pytest.raises((ray_tpu.ObjectLostError, ray_tpu.GetTimeoutError)):
        ray_tpu.get(ref, timeout=10)


def test_head_restart_with_persistence(tmp_path):
    """Control-plane fault tolerance: restart the head; daemons and drivers
    reconnect, named actors stay resolvable, KV survives (reference: GCS
    restart from Redis; raylet HandleNotifyGCSRestart)."""
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster(persist_path=str(tmp_path / "head_snapshot.pkl"))
    c.add_node(num_cpus=4)
    rt = c.connect()
    old_runtime = global_worker.runtime
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        @remote
        class KV:
            def __init__(self):
                self.d = {}

            def put(self, k, v):
                self.d[k] = v
                return "ok"

            def get(self, k):
                return self.d.get(k)

        h = KV.options(name="survivor").remote()
        assert ray_tpu.get(h.put.remote("a", 1), timeout=60) == "ok"
        rt.kv_put("durable", b"value")
        time.sleep(0.6)  # let the persist loop flush

        c.restart_head()
        time.sleep(0.5)  # daemons reconnect on their heartbeat

        # Driver RPC reconnects transparently; durable state is back.
        assert rt.kv_get("durable") == b"value"
        h2 = ray_tpu.get_actor("survivor")
        # The actor process never died — calls flow to the same worker and
        # its in-memory state is intact.
        assert ray_tpu.get(h2.get.remote("a"), timeout=60) == 1
        # New work schedules normally on the reconnected node.
        @remote
        def ping():
            return "alive"

        assert ray_tpu.get(ping.remote(), timeout=60) == "alive"
    finally:
        rt.shutdown()
        c.shutdown()
        global_worker.runtime = old_runtime
        config_mod.set_config(config_mod.Config.load())


def test_chunked_pull_large_object(cluster, monkeypatch):
    """Large results move node-to-node in bounded pipelined chunks
    (reference: pull_manager.h:50 bounded pulls + ObjectBufferPool chunks).
    The producer runs on a SECOND node so its result lives in a different
    shm arena and must cross the wire."""
    import numpy as np

    from ray_tpu.core.cluster.runtime import ClusterRuntime

    monkeypatch.setattr(ClusterRuntime, "PULL_CHUNK", 256 * 1024)
    pulls = []
    orig = ClusterRuntime._pull_chunked

    def counting_pull(self, peer, ref, first, total):
        pulls.append(total)
        return orig(self, peer, ref, first, total)

    monkeypatch.setattr(ClusterRuntime, "_pull_chunked", counting_pull)
    # Pin the RPC fallback: the native data plane would otherwise serve
    # this pull before the chunked path (covered by its own test below).
    monkeypatch.setattr(ClusterRuntime, "_native_pull",
                        lambda self, node, ref: None)
    cluster.add_node(num_cpus=2, resources={"far": 1.0})
    time.sleep(0.3)

    @remote(resources={"far": 1.0})
    def big():
        import numpy as np
        return np.arange(1_500_000, dtype=np.float32)  # ~6MB -> ~24 chunks

    ref = big.remote()
    arr = ray_tpu.get(ref, timeout=120)
    assert arr.shape == (1_500_000,)
    np.testing.assert_allclose(arr[:5], [0, 1, 2, 3, 4])
    assert float(arr[-1]) == 1_499_999.0
    assert pulls and pulls[0] > 1_000_000  # the chunked path actually ran


def test_native_transfer_data_plane(cluster, monkeypatch):
    """Large cross-node results ride the C++ arena-to-arena transfer plane
    (src/transfer/transfer.cc): the holder node's transfer server streams
    bytes out of its shm arena into the puller's (reference: the object
    manager's native data path, object_manager.h + pull_manager.h)."""
    import numpy as np

    from ray_tpu.core.cluster.runtime import ClusterRuntime

    native = []
    orig = ClusterRuntime._native_pull

    def counting_native(self, node, ref):
        out = orig(self, node, ref)
        native.append((node, out is not None))
        return out

    if global_worker.runtime.shm is None:
        pytest.skip("native toolchain unavailable")
    monkeypatch.setattr(ClusterRuntime, "_native_pull", counting_native)
    cluster.add_node(num_cpus=2, resources={"xfer": 1.0})
    time.sleep(0.3)

    # every alive node advertises its transfer server
    from ray_tpu.util.state.api import list_nodes
    assert all(n.get("transfer_addr") for n in list_nodes() if n["alive"])

    @remote(resources={"xfer": 1.0})
    def big():
        import numpy as np
        return np.arange(2_000_000, dtype=np.float32)  # ~8MB

    arr = ray_tpu.get(big.remote(), timeout=120)
    assert arr.shape == (2_000_000,) and float(arr[-1]) == 1_999_999.0
    assert any(ok for _node, ok in native), native  # native path served it


def test_task_scheduling_strategies(tmp_path):
    """SPREAD round-robins tasks across feasible nodes; NODE_AFFINITY pins
    (hard) or falls back (soft) — reference: raylet scheduling policies +
    util/scheduling_strategies.py."""
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    c = Cluster()
    n1 = c.add_node(num_cpus=2, node_id="node-aaa")
    n2 = c.add_node(num_cpus=2, node_id="node-bbb")
    rt = c.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        @remote
        def where():
            return os.environ["RTPU_NODE_ID"]

        # SPREAD: consecutive tasks land on BOTH nodes
        spread = where.options(scheduling_strategy="SPREAD", num_cpus=1)
        nodes = set(ray_tpu.get([spread.remote() for _ in range(4)],
                                timeout=120))
        assert nodes == {"node-aaa", "node-bbb"}, nodes

        # NODE_AFFINITY hard: every task lands on the pinned node
        pin = where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="node-bbb"), num_cpus=1)
        assert set(ray_tpu.get([pin.remote() for _ in range(3)],
                               timeout=120)) == {"node-bbb"}

        # NODE_AFFINITY soft to a dead node: falls back to a live one
        soft = where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="node-dead", soft=True), num_cpus=1)
        assert ray_tpu.get(soft.remote(), timeout=120) in ("node-aaa",
                                                           "node-bbb")

        # hard affinity to a dead node fails loudly
        hard = where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="node-dead"), num_cpus=1)
        with pytest.raises(Exception):
            ray_tpu.get(hard.remote(), timeout=60)
    finally:
        rt.shutdown()
        c.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode) = old


def test_head_wal_survives_hard_crash(tmp_path):
    """Write-through persistence: mutations logged BETWEEN snapshots must
    survive a kill -9 of the head (reference: redis_store_client.cc persists
    per mutation — an interval snapshot alone would lose everything since
    the last flush). Drives the HeadServer tables directly: no snapshot is
    ever written, so recovery comes purely from the WAL."""
    import asyncio

    from ray_tpu.core.cluster.head import HeadServer

    path = str(tmp_path / "snap.pkl")

    async def mutate(head):
        await head._kv_put(None, "ns", "k1", b"v1")
        await head._kv_put(None, "ns", "k2", b"v2")
        await head._kv_del(None, "ns", "k2")
        # actor registration straight into the FSM tables (no cluster):
        from ray_tpu.core.cluster.head import ActorInfo

        info = ActorInfo(actor_id="a" * 32, name="walled",
                         namespace="default", spec_blob=b"blob",
                         resources={"CPU": 1.0})
        head.actors[info.actor_id] = info
        head.named_actors[("default", "walled")] = info.actor_id
        head._log_mutation("actor", info.actor_id, info)
        # placement group record
        head.pgs["pg1"] = {"state": "PENDING", "bundles": [{"CPU": 1}],
                           "strategy": "PACK", "assignment": None,
                           "name": None}
        head._log_mutation("pg", "pg1", dict(head.pgs["pg1"]))

    head = HeadServer(port=0, persist_path=path)
    asyncio.run(mutate(head))
    # kill -9: no stop(), no snapshot flush. The WAL was flushed per record.
    del head

    head2 = HeadServer(port=0, persist_path=path)
    assert head2.kv["ns"]["k1"] == b"v1"
    assert "k2" not in head2.kv["ns"]
    assert head2.named_actors[("default", "walled")] == "a" * 32
    assert head2.actors["a" * 32].spec_blob == b"blob"
    assert head2.pgs["pg1"]["strategy"] == "PACK"

    # Snapshot compaction: write the snapshot (rotates the WAL), mutate
    # again, crash again — both halves must be restored.
    head2._write_snapshot(head2._snapshot_state())
    asyncio.run(head2._kv_put(None, "ns", "k3", b"v3"))
    del head2

    head3 = HeadServer(port=0, persist_path=path)
    assert head3.kv["ns"]["k1"] == b"v1"
    assert head3.kv["ns"]["k3"] == b"v3"
    assert head3.actors["a" * 32].name == "walled"


def test_head_crash_after_mutation_cluster(tmp_path):
    """End-to-end: register a named actor and KV, hard-crash the head
    IMMEDIATELY (no snapshot window), restart — nothing is lost."""
    os.environ["RTPU_HEALTH_CHECK_PERIOD_S"] = "0.2"
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    c = Cluster(persist_path=str(tmp_path / "snap.pkl"))
    c.add_node(num_cpus=2)
    rt = c.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        @remote
        class S:
            def ping(self):
                return "pong"

        h = S.options(name="crashproof").remote()
        assert ray_tpu.get(h.ping.remote(), timeout=60) == "pong"
        rt.kv_put("k", b"v")
        c.crash_head()  # immediately: between interval snapshots
        time.sleep(0.5)  # daemons reconnect on heartbeat
        assert rt.kv_get("k") == b"v"
        h2 = ray_tpu.get_actor("crashproof")
        assert ray_tpu.get(h2.ping.remote(), timeout=60) == "pong"
    finally:
        rt.shutdown()
        c.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode) = old


def test_data_locality_lease_placement(tmp_path):
    """A task consuming a large remote object leases from the node HOLDING
    it, without a transfer (reference: lease_policy.cc locality-aware lease
    policy; SURVEY §3.2 step 2 — the chosen raylet is data-locality aware)."""
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    c = Cluster()
    c.add_node(num_cpus=2, node_id="node-src")
    c.add_node(num_cpus=2, node_id="node-holder")
    rt = c.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        @remote
        def produce():
            return b"z" * (10 * 1024 * 1024)  # non-inline: stays at executor

        @remote
        def consume(blob):
            return (os.environ["RTPU_NODE_ID"], len(blob))

        big = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id="node-holder"), num_cpus=1).remote()
        ray_tpu.wait([big], timeout=120)
        node, size = ray_tpu.get(consume.remote(big), timeout=120)
        assert size == 10 * 1024 * 1024
        assert node == "node-holder", f"consumer ran on {node}, not holder"
    finally:
        rt.shutdown()
        c.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode) = old


def test_broadcast_relay_distribution(tmp_path):
    """One-to-many distribution: N nodes pulling the same large object are
    spread across copies as they appear instead of all hammering the owner
    (reference: push_manager.h relay/broadcast; BASELINE 1GiB->50 nodes).
    The owner bounds outstanding referrals per copy, so a simultaneous
    fan-out cannot exceed 2x concurrent transfers from the source.

    Forces the TCP transfer plane: same-host pullers would otherwise read
    the source arena directly (no relay copies form on one host)."""
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    with _forced_tcp_plane():
        _run_broadcast_relay_distribution(NodeAffinitySchedulingStrategy)


def _forced_tcp_plane():
    """Context manager: disable same-host arena reads for the enclosed
    cluster (env + config reload), restoring both even when cluster
    setup fails — a leaked override would silently change which data
    plane every later test exercises."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        from ray_tpu.utils import config as config_mod

        os.environ["RTPU_TRANSFER_SAME_HOST_ARENA"] = "0"
        config_mod.set_config(config_mod.Config.load())
        try:
            yield
        finally:
            os.environ.pop("RTPU_TRANSFER_SAME_HOST_ARENA", None)
            config_mod.set_config(config_mod.Config.load())

    return _cm()


def _run_broadcast_relay_distribution(NodeAffinitySchedulingStrategy):
    c = Cluster()
    src_node = c.add_node(num_cpus=1, node_id="bsrc")
    nodes = [c.add_node(num_cpus=2, node_id=f"bnode-{i}") for i in range(4)]
    rt = c.connect(src_node)  # the object lives on bsrc: EVERY consumer pulls
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    try:
        payload = b"b" * (8 * 1024 * 1024)  # >= RELAY_MIN_BYTES, multi-chunk
        big = ray_tpu.put(payload)

        @remote
        def consume(blob):
            import time as _t

            _t.sleep(2.0)  # hold the borrow: the cached copy stays in the
            # relay set long enough for later pullers to be referred to it
            # (retraction-on-release would otherwise race the fan-out on a
            # loaded box)
            return len(blob)

        refs = []
        for i in range(8):
            node = f"bnode-{i % 4}"
            refs.append(consume.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=node), num_cpus=1).remote(big))
        out = ray_tpu.get(refs, timeout=180)
        assert out == [len(payload)] * 8
        counts = rt.refer_counts.get(big.id, {})
        assert counts, "owner never issued relay referrals"
        # Referrals were spread beyond the single source copy: pullers that
        # cached a copy joined the relay set and served later pullers. (The
        # final _replicas set may already be empty again — borrowers
        # RETRACT their entry when task completion releases their cache.)
        assert len(counts) >= 2, f"all pulls referred to one copy: {counts}"
    finally:
        rt.shutdown()
        c.shutdown()
        (global_worker.runtime, global_worker.worker_id,
         global_worker.node_id, global_worker.mode) = old


def test_promoted_relay_copy_is_pinned():
    """When the owner loses its primary copy and promotes a borrower's
    cached copy, it pins the copy at the holder first — otherwise the
    borrow-cache TTL sweep deletes the only surviving bytes and a put()
    object (no lineage) is permanently lost (ADVICE r3).

    Forces the TCP transfer plane: a same-host borrower reads the owner's
    arena directly and never caches the copy this test is about."""
    with _forced_tcp_plane():
        _run_promoted_relay_copy_is_pinned()


def _run_promoted_relay_copy_is_pinned():
    c = Cluster()
    n1 = c.add_node(num_cpus=1, node_id="pin-owner")
    n2 = c.add_node(num_cpus=1, node_id="pin-holder")
    rt_owner = c.connect(n1)
    rt_b = c.connect(n2)
    try:
        payload = b"p" * (2 * 1024 * 1024)  # >= RELAY_MIN_BYTES
        ref = rt_owner.put(payload)
        # Borrower pulls + caches the copy and reports itself a holder.
        assert rt_b.get([ref], timeout=60) == [payload]
        deadline = time.monotonic() + 10
        while rt_b.worker_id.hex() not in \
                rt_owner._replicas.get(ref.id, set()):
            assert time.monotonic() < deadline, "holder never reported"
            time.sleep(0.05)
        # Borrower releases: its copy moves to the TTL'd borrow cache.
        class _Rec:
            owner_id = rt_owner.worker_id
            lineage_task = None
        rt_b._release_object(ref.id, _Rec())
        assert ref.id in rt_b._borrow_cache
        # The owner loses its primary (simulated crash of its store).
        rt_owner.store.delete(ref.id)
        if rt_owner.shm is not None:
            try:
                rt_owner.shm.delete(ref.id.binary())
            except Exception:
                pass
        # A borrower reports the loss; the owner must promote AND pin.
        res = rt_b._peer(rt_owner.addr).call(
            "report_lost", oid=ref.id.hex(),
            holder=rt_owner.worker_id.hex(), timeout=15)
        assert res["state"] == "present"
        assert rt_owner._locations[ref.id] == rt_b.worker_id.hex()
        assert ref.id in rt_b._pinned_borrows
        assert ref.id not in rt_b._borrow_cache
        # The sweep must not touch the pinned copy even past TTL.
        old_ttl = type(rt_b).BORROW_CACHE_TTL_S
        try:
            type(rt_b).BORROW_CACHE_TTL_S = 0.0
            rt_b._sweep_borrow_cache()
        finally:
            type(rt_b).BORROW_CACHE_TTL_S = old_ttl
        assert rt_b._local_size(ref.id) is not None, "sweep deleted the pin"
        # And a third party can still fetch the bytes end-to-end.
        rt_c = c.connect(n1)
        try:
            assert rt_c.get([ref], timeout=60) == [payload]
        finally:
            rt_c.shutdown()
    finally:
        rt_b.shutdown()
        rt_owner.shutdown()
        c.shutdown()


def test_same_host_arena_view_serves_without_transfer():
    """Same-host zero-copy plane: a puller whose host matches the holder
    node's boot id maps that node's arena and serves get() from a pinned
    view — no wire transfer, no local copy, read-only plasma semantics."""
    import numpy as np

    c = Cluster()
    n1 = c.add_node(num_cpus=1, node_id="shv-a")
    n2 = c.add_node(num_cpus=1, node_id="shv-b")
    rt_a = c.connect(n1)
    rt_b = c.connect(n2)
    try:
        if rt_a.shm is None or rt_b.shm is None:
            pytest.skip("native shm store unavailable")
        payload = np.arange(1_000_000, dtype=np.float32)  # ~4MB
        ref = rt_a.put(payload)
        (out,) = rt_b.get([ref], timeout=60)
        np.testing.assert_array_equal(out, payload)
        assert out.flags.writeable is False  # read-only get() contract
        # Served straight from the peer arena: mapped it, cached nothing.
        assert rt_b._peer_arenas, "peer arena was never mapped"
        assert not rt_b._local_contains(ref.id)
        del out
    finally:
        rt_b.shutdown()
        rt_a.shutdown()
        c.shutdown()


def test_gossip_resource_view_converges_and_spills():
    """Resource views disseminate daemon-to-daemon (reference:
    src/ray/ray_syncer/ bidi-stream view sync — the head seeds MEMBERSHIP
    only): every daemon converges to a full peer view, and spillback
    decisions use the gossiped view without a head list_nodes round-trip."""
    c = Cluster()
    d1 = c.add_node(num_cpus=1, node_id="gsp-1")
    d2 = c.add_node(num_cpus=4, node_id="gsp-2")
    d3 = c.add_node(num_cpus=2, node_id="gsp-3")
    try:
        # 1. convergence: each daemon's gossiped view covers all peers.
        deadline = time.monotonic() + 20
        daemons = {"gsp-1": d1, "gsp-2": d2, "gsp-3": d3}
        while time.monotonic() < deadline:
            ok = all(
                set(d._gossip_view) >= (set(daemons) - {nid})
                for nid, d in daemons.items())
            if ok:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                {nid: sorted(d._gossip_view) for nid, d in daemons.items()})
        # availability data rode the ring, not the head
        view = d1._gossip_nodes_view()
        assert view["gsp-2"]["resources"]["CPU"] == 4.0
        assert view["gsp-2"]["alive"] and view["gsp-3"]["alive"]

        # 2. spillback resolves from the gossiped view even when the head
        # cannot answer list_nodes.
        orig_call = d1._head.call

        async def no_list_nodes(method, **kw):
            if method == "list_nodes":
                raise RuntimeError("head view unavailable (simulated)")
            return await orig_call(method, **kw)

        d1._head.call = no_list_nodes
        try:
            rt = c.connect(d1)
            try:
                res = rt._io.run(d1._request_lease(
                    None, {"CPU": 3.0}, timeout=5))
                # gsp-1 (1 CPU) can't fit 3 CPUs; gossip view says gsp-2 can.
                assert res.get("spill"), res
                assert tuple(res["spill"]) == (d2.rpc.host, d2.rpc.port)
            finally:
                rt.shutdown()
        finally:
            d1._head.call = orig_call
    finally:
        c.shutdown()
