"""JAX LLM inference engine: continuous batching over a slot KV cache.

Capability parity with the reference's serving engine (reference: ray.llm
wraps vLLM — _internal/serve/engines/vllm/vllm_models.py:148; continuous
batching + paged KV are vLLM internals). TPU-native design instead of a
wrapper:

- **Static shapes everywhere** (XLA compiles once per prefill bucket):
  the KV cache is a dense [layers, slots, kv_heads, max_seq, head_dim]
  pool; a sequence owns one slot for its lifetime — slot admission is the
  scheduling unit, like vLLM's paged blocks but shaped for XLA/TPU (no
  dynamic page tables; dynamic_update_slice writes, masked reads).
- **Continuous batching**: every engine tick admits waiting requests into
  free slots (bucketed prefill) and then decodes ALL active slots in one
  batched jitted step — new requests join mid-flight without stalling
  running ones.
- **Roundtrip-lean scheduling**: decode runs up to ``decode_burst`` steps
  per dispatch (sampled tokens fed forward on device via lax.scan), and a
  tick's prefill first-token fetches are deferred until its decode work is
  queued — so one tick costs ONE host⇄device roundtrip regardless of how
  many prefills and decode tokens it covers. This is what makes the engine
  fast when the accelerator is remote (tunneled) or the model is small
  enough that dispatch latency rivals compute.
- **Sampling on-device**: temperature/top-k/top-p in fp32 logits, one
  fused jit; greedy when temperature == 0.
- Cache buffers are donated through jit so XLA updates them in place.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.devtools.annotations import guarded_by
from ray_tpu.llm.config import LLMConfig, SamplingParams
from ray_tpu.util import tracing
from ray_tpu.llm.tokenizer import get_tokenizer
from ray_tpu.models.llama import LlamaConfig, init_params
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies

logger = logging.getLogger(__name__)

NEG_INF = -1e30


def _lcp(a, b, cap: int) -> int:
    n = min(len(a), len(b), cap)
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


def init_kv_cache(cfg: LlamaConfig, max_slots: int, max_seq: int):
    shape = (cfg.num_layers, max_slots, cfg.num_kv_heads, max_seq,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.jnp_dtype),
            "v": jnp.zeros(shape, cfg.jnp_dtype)}


def _project_qkv(cfg: LlamaConfig, lp, xn, b, s):
    q = (xn @ lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = (xn @ lp["wk"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = (xn @ lp["wv"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    return (q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3))


def _repeat_kv(x, n_rep: int):
    if n_rep == 1:
        return x
    b, h, s, d = x.shape
    return jnp.broadcast_to(x[:, :, None], (b, h, n_rep, s, d)).reshape(
        b, h * n_rep, s, d)


def _mlp(cfg: LlamaConfig, lp, x):
    dt = x.dtype
    xn = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu((xn @ lp["w_gate"]).astype(jnp.float32)).astype(dt)
    up = xn @ lp["w_up"]
    return x + ((gate * up) @ lp["w_down"]).astype(dt)


def _lm_head(cfg: LlamaConfig, params, x):
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = (params["embed_tokens"].T if cfg.tie_embeddings
            else params["lm_head"])
    return x.astype(jnp.float32) @ head.astype(jnp.float32)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def prefill(cfg: LlamaConfig, params, cache, tokens, length, slot):
    """Prefill ONE sequence into cache slot ``slot``.

    tokens: [S_bucket] (padded), length: scalar int32 (true prompt length),
    returns (cache, next_token_logits [V]).
    """
    s = tokens.shape[0]
    x = params["embed_tokens"][tokens][None]  # [1, S, H]
    positions = jnp.arange(s)
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    n_rep = cfg.num_heads // cfg.num_kv_heads
    causal = (positions[None, :] <= positions[:, None])  # [S, S]
    valid = positions[None, :] < length
    mask = (causal & valid)[None, None]  # [1, 1, S, S]

    def body(x, scanned):
        lp, k_l, v_l = scanned  # k_l/v_l: [slots, Hkv, max_seq, D]
        b, s_, _ = x.shape
        xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, xn, b, s_)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        # Write this layer's K/V into the slot (positions 0..S).
        k_l = lax.dynamic_update_slice(k_l, k[0].astype(k_l.dtype)[None],
                                       (slot, 0, 0, 0))
        v_l = lax.dynamic_update_slice(v_l, v[0].astype(v_l.dtype)[None],
                                       (slot, 0, 0, 0))
        kr, vr = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kr).astype(jnp.float32)
        scores = scores / np.sqrt(cfg.head_dim) + jnp.where(mask, 0.0, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, vr)
        o = o.transpose(0, 2, 1, 3).reshape(b, s_, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(cfg, lp, x)
        return x, (k_l, v_l)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(cfg, params, x[0])  # [S, V]
    last = logits[jnp.maximum(length - 1, 0)]
    return {"k": new_k, "v": new_v}, last


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def prefill_chunk(cfg: LlamaConfig, params, cache, tokens, kv_len, length,
                  slot):
    """Prefill ONE chunk of one sequence (chunked prefill — long prompts are
    split so decode steps interleave between chunks instead of stalling
    behind a whole-prompt prefill; reference shape: vLLM chunked prefill /
    enable_chunked_prefill).

    tokens: [C] chunk (padded), kv_len: tokens already cached for this slot,
    length: true total prompt length. Queries attend to cache[0..kv_len) +
    the chunk's own causal prefix. Returns (cache, last-token logits [V]).
    """
    c = tokens.shape[0]
    max_seq = cache["k"].shape[3]
    x = params["embed_tokens"][tokens][None]  # [1, C, H]
    positions = kv_len + jnp.arange(c)
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    n_rep = cfg.num_heads // cfg.num_kv_heads
    kpos = jnp.arange(max_seq)
    # [C, max_seq]: causal vs absolute kv position, limited to real tokens.
    mask = (kpos[None, :] <= positions[:, None]) & (kpos[None, :] < length)
    mask = mask[None, None]

    def body(x, scanned):
        lp, k_l, v_l = scanned  # k_l/v_l: [slots, Hkv, max_seq, D]
        b, c_, _ = x.shape
        xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, xn, b, c_)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        k_l = lax.dynamic_update_slice(k_l, k[0].astype(k_l.dtype)[None],
                                       (slot, 0, kv_len, 0))
        v_l = lax.dynamic_update_slice(v_l, v[0].astype(v_l.dtype)[None],
                                       (slot, 0, kv_len, 0))
        ks = lax.dynamic_slice_in_dim(k_l, slot, 1, 0).astype(x.dtype)
        vs = lax.dynamic_slice_in_dim(v_l, slot, 1, 0).astype(x.dtype)
        kr, vr = _repeat_kv(ks, n_rep), _repeat_kv(vs, n_rep)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kr).astype(jnp.float32)
        scores = scores / np.sqrt(cfg.head_dim) + jnp.where(mask, 0.0, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, vr)
        o = o.transpose(0, 2, 1, 3).reshape(b, c_, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(cfg, lp, x)
        return x, (k_l, v_l)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(cfg, params, x[0])  # [C, V]
    last = logits[jnp.clip(length - 1 - kv_len, 0, c - 1)]
    return {"k": new_k, "v": new_v}, last


def _decode_step_impl(cfg: LlamaConfig, params, cache, tokens, positions,
                      write_mask=None):
    """One decode step for EVERY slot.

    tokens: [B] (last sampled token per slot), positions: [B] (where each
    token is written/attends from). write_mask: [B] bool — slots mid-prefill
    or empty must not have garbage K/V written into their cache (False =
    keep the existing cache line). Returns (cache, logits [B, V]).

    Exactly the K=1 case of the multi-token body speculative verification
    uses — ONE implementation of the masked-attention/KV-write math, so
    the two paths can never diverge.
    """
    if write_mask is None:
        write_mask = jnp.ones(tokens.shape, bool)
    cache, logits = _multi_token_impl(cfg, params, cache, tokens[:, None],
                                      positions, write_mask)
    return cache, logits[:, 0]


def _multi_token_impl(cfg: LlamaConfig, params, cache, tokens, positions0,
                      write_mask):
    """Consume K tokens per slot in one pass against the KV cache.

    tokens: [B, K]; positions0: [B] — tokens[:, j] is written at
    positions0 + j (contiguous); query j attends kv through its own
    position. Returns (cache, logits [B, K, V])."""
    b, k = tokens.shape
    max_seq = cache["k"].shape[3]
    x = params["embed_tokens"][tokens]  # [B, K, H]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling)
    n_rep = cfg.num_heads // cfg.num_kv_heads
    positions = positions0[:, None] + jnp.arange(k)[None, :]  # [B, K]
    kv_mask = (jnp.arange(max_seq)[None, None, :]
               <= positions[:, :, None])[:, None]  # [B, 1, K, S]

    def write(cache_l, new, p0):
        # cache_l: [B, Hkv, S, D]; new: [B, Hkv, K, D]; p0: [B]
        # Slice-merge-write touches only the K-row window: a full-line
        # jnp.where(en, updated, c) would read+write the whole [Hkv, S, D]
        # cache line per slot per layer on every decode step.
        def upd(c, n, p, en):
            window = lax.dynamic_slice(
                c, (0, p, 0), (c.shape[0], n.shape[1], c.shape[2]))
            merged = jnp.where(en, n.astype(c.dtype), window)
            return lax.dynamic_update_slice(c, merged, (0, p, 0))
        return jax.vmap(upd)(cache_l, new, p0, write_mask)

    def body(x, scanned):
        lp, k_l, v_l = scanned
        xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, kk, v = _project_qkv(cfg, lp, xn, b, k)
        q = apply_rope(q, positions, inv_freq)
        kk = apply_rope(kk, positions, inv_freq)
        k_l = write(k_l, kk, positions0)
        v_l = write(v_l, v, positions0)
        kr = _repeat_kv(k_l.astype(x.dtype), n_rep)  # [B, H, S, D]
        vr = _repeat_kv(v_l.astype(x.dtype), n_rep)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kr).astype(jnp.float32)
        scores = scores / np.sqrt(cfg.head_dim)
        scores = scores + jnp.where(kv_mask, 0.0, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, vr)
        o = o.transpose(0, 2, 1, 3).reshape(b, k, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(cfg, lp, x)
        return x, (k_l, v_l)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(cfg, params, x)  # [B, K, V]
    return {"k": new_k, "v": new_v}, logits


decode_step = partial(jax.jit, static_argnums=(0,),
                      donate_argnums=(2,))(_decode_step_impl)


@partial(jax.jit, static_argnums=(0, 9, 10), donate_argnums=(2,))
def decode_burst(cfg: LlamaConfig, params, cache, token0, positions0,
                 write_mask, temps, top_ps, key, steps: int,
                 need_top_p: bool = True):
    """``steps`` chained decode+sample ticks in ONE dispatch: the sampled
    token feeds the next step on device (lax.scan), so the host⇄device
    roundtrip — which dominates per-token latency for small models and for
    remote/tunneled accelerators — is paid once per ``steps`` tokens
    instead of per token. Greedy/temperature/top-p sampling only (top-k
    needs a static k; the engine falls back to single-step ticks).
    Returns (cache, tokens [steps, B])."""

    def step(carry, j):
        c, tok, pos = carry
        c, logits = _decode_step_impl(cfg, params, c, tok, pos, write_mask)
        nxt = sample_tokens(logits.astype(jnp.float32), temps, top_ps, 0,
                            jax.random.fold_in(key, j),
                            need_top_p).astype(jnp.int32)
        return (c, nxt, pos + 1), nxt

    (cache, _, _), toks = lax.scan(step, (cache, token0, positions0),
                                   jnp.arange(steps))
    return cache, toks


# ---------------------------------------------------------------------------
# Speculative decoding (reference capability: the vLLM speculative-decoding
# path behind the reference's llm serving stack). Decode is HBM-bound on
# TPU — one token per full weight read; verifying K draft tokens in one
# forward amortizes the weight traffic K-fold when the draft is right.
# Rollback is FREE in this cache design: entries written beyond the
# accepted prefix sit at positions >= next_pos, which every later read
# masks (kv_pos <= position) and every later write overwrites.


@partial(jax.jit, static_argnums=(0, 5), donate_argnums=(2,))
def draft_propose(cfg: LlamaConfig, params, cache, token0, positions0,
                  k: int, write_mask):
    """Greedy-propose ``k`` tokens with the draft model in ONE dispatch
    (lax.scan over its decode step). Writes draft KV for token0 and the
    first k-1 proposals. Returns (cache, proposals [B, k])."""

    def step(carry, _):
        c, tok, pos = carry
        c, logits = _decode_step_impl(cfg, params, c, tok, pos, write_mask)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (c, nxt, pos + 1), nxt

    # k+1 iterations: the extra step writes the LAST proposal's KV inside
    # this same dispatch (its own proposal is discarded), so a
    # full-acceptance tick needs no separate one-token catch-up prefill.
    (cache, _, _), toks = lax.scan(step, (cache, token0, positions0),
                                   None, length=k + 1)
    return cache, toks.T[:, :k]  # [B, k]


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def spec_verify_step(cfg: LlamaConfig, params, cache, tokens, positions0,
                     write_mask):
    """Target forward over K tokens per slot in one pass (the jitted
    multi-token body decode_step is the K=1 case of).

    tokens: [B, K] — the last sampled token followed by the draft
    proposals; positions0: [B] — where tokens[:, 0] is written. Writes
    K/V for all K positions (contiguous) and returns (cache,
    logits [B, K, V]): logits[:, j] scores the token at position
    positions0 + j + 1, which is what acceptance compares against."""
    return _multi_token_impl(cfg, params, cache, tokens, positions0,
                             write_mask)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
def copy_prefix_kv(cfg: LlamaConfig, cache, src_slot, dst_slot):
    """Copy one slot's whole KV line to another slot, all layers at once
    (prefix-cache adoption from a LIVE donor). Copying the full max_seq
    line is safe: positions beyond the adopted prefix are masked by
    ``length``/``positions`` in prefill_chunk/decode_step, and the copy is
    pure HBM bandwidth — orders of magnitude cheaper than recomputing the
    prefix (vLLM APC makes the same recompute-vs-reuse trade)."""
    k_line = lax.dynamic_slice_in_dim(cache["k"], src_slot, 1, 1)
    v_line = lax.dynamic_slice_in_dim(cache["v"], src_slot, 1, 1)
    return {
        "k": lax.dynamic_update_slice(cache["k"], k_line,
                                      (0, dst_slot, 0, 0, 0)),
        "v": lax.dynamic_update_slice(cache["v"], v_line,
                                      (0, dst_slot, 0, 0, 0)),
    }


# ---------------------------------------------------------------------------
# Block-pooled KV cache (reference capability: vLLM PagedAttention behind
# ray.llm — vllm_models.py:148 — re-designed TPU-first). The pool is
# [layers, num_blocks, Hkv, block_size, D]; a per-slot block TABLE maps
# virtual position p to pool block table[slot, p // block_size]. All
# shapes are static: tables are int32 arrays, reads gather the slot's
# blocks into a virtual [max_blocks*block_size] sequence (the same masked
# attention the dense path runs), writes scatter whole blocks (prefill —
# chunks are block-aligned) or single rows (decode). No device-side page
# tables, no dynamic shapes — XLA sees gathers and scatters it can fuse.


def init_kv_cache_blocked(cfg: LlamaConfig, num_blocks: int,
                          block_size: int):
    shape = (cfg.num_layers, num_blocks, cfg.num_kv_heads, block_size,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.jnp_dtype),
            "v": jnp.zeros(shape, cfg.jnp_dtype)}


def _gather_slot_kv(kv_l, table_row, dtype):
    """kv_l [NB, Hkv, bs, D] + table_row [MB] -> [1, Hkv, MB*bs, D]
    virtual sequence for one slot."""
    g = kv_l[table_row]                       # [MB, Hkv, bs, D]
    mb, hkv, bs, d = g.shape
    return g.transpose(1, 0, 2, 3).reshape(1, hkv, mb * bs, d).astype(dtype)


def _gather_batch_kv(kv_l, tables, dtype):
    """kv_l [NB, Hkv, bs, D] + tables [B, MB] -> [B, Hkv, MB*bs, D]."""
    g = kv_l[tables]                          # [B, MB, Hkv, bs, D]
    b, mb, hkv, bs, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, hkv, mb * bs, d).astype(
        dtype)


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def prefill_chunk_blocked(cfg: LlamaConfig, params, cache, table_row,
                          tokens, kv_len, length):
    """Blocked-cache chunked prefill for ONE slot. ``table_row`` [MB] is
    the slot's block table; the engine guarantees kv_len and the chunk
    bucket are multiples of block_size, so the chunk writes whole blocks.
    Returns (cache, last-token logits [V])."""
    c = tokens.shape[0]
    bs = cache["k"].shape[3]
    mb = table_row.shape[0]
    nblk = c // bs
    x = params["embed_tokens"][tokens][None]  # [1, C, H]
    positions = kv_len + jnp.arange(c)
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling)
    n_rep = cfg.num_heads // cfg.num_kv_heads
    kpos = jnp.arange(mb * bs)
    mask = (kpos[None, :] <= positions[:, None]) & (kpos[None, :] < length)
    mask = mask[None, None]
    blk0 = kv_len // bs  # first block index within the table (traced)

    def body(x, scanned):
        lp, k_l, v_l = scanned  # [NB, Hkv, bs, D]
        b, c_, _ = x.shape
        xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, lp, xn, b, c_)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        # Whole-block writes: chunk j lands in pool block table[blk0+j].
        kb = k[0].astype(k_l.dtype)  # [Hkv, C, D]
        vb = v[0].astype(v_l.dtype)
        for j in range(nblk):
            blk = table_row[blk0 + j]
            k_l = k_l.at[blk].set(
                lax.dynamic_slice_in_dim(kb, j * bs, bs, 1))
            v_l = v_l.at[blk].set(
                lax.dynamic_slice_in_dim(vb, j * bs, bs, 1))
        ks = _gather_slot_kv(k_l, table_row, x.dtype)
        vs = _gather_slot_kv(v_l, table_row, x.dtype)
        kr, vr = _repeat_kv(ks, n_rep), _repeat_kv(vs, n_rep)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kr).astype(jnp.float32)
        scores = scores / np.sqrt(cfg.head_dim) + jnp.where(mask, 0.0,
                                                            NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, vr)
        o = o.transpose(0, 2, 1, 3).reshape(b, c_, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(cfg, lp, x)
        return x, (k_l, v_l)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(cfg, params, x[0])  # [C, V]
    last = logits[jnp.clip(length - 1 - kv_len, 0, c - 1)]
    return {"k": new_k, "v": new_v}, last


def _multi_token_impl_blocked(cfg: LlamaConfig, params, cache, tables,
                              tokens, positions0, write_mask):
    """Blocked-cache analog of _multi_token_impl: K tokens per slot
    against the pool through per-slot block tables [B, MB]. Decode writes
    are row scatters (block = tables[b, p//bs], row = p%bs); masked slots
    scatter out of bounds and are dropped."""
    b, k = tokens.shape
    _, nb, _, bs, _ = cache["k"].shape
    mb = tables.shape[1]
    x = params["embed_tokens"][tokens]  # [B, K, H]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling)
    n_rep = cfg.num_heads // cfg.num_kv_heads
    positions = positions0[:, None] + jnp.arange(k)[None, :]  # [B, K]
    kv_mask = (jnp.arange(mb * bs)[None, None, :]
               <= positions[:, :, None])[:, None]  # [B, 1, K, S]
    # Per-token pool coordinates; masked writes target block NB → dropped.
    blk = jnp.take_along_axis(tables, positions // bs, axis=1)  # [B, K]
    blk = jnp.where(write_mask[:, None], blk, nb)
    row = positions % bs

    def write(cache_l, new):
        # cache_l [NB, Hkv, bs, D]; new [B, Hkv, K, D] -> rows [B, K, Hkv, D]
        rows = new.transpose(0, 2, 1, 3).astype(cache_l.dtype)
        return cache_l.at[blk, :, row, :].set(rows, mode="drop")

    def body(x, scanned):
        lp, k_l, v_l = scanned
        xn = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q, kk, v = _project_qkv(cfg, lp, xn, b, k)
        q = apply_rope(q, positions, inv_freq)
        kk = apply_rope(kk, positions, inv_freq)
        k_l = write(k_l, kk)
        v_l = write(v_l, v)
        kr = _repeat_kv(_gather_batch_kv(k_l, tables, x.dtype), n_rep)
        vr = _repeat_kv(_gather_batch_kv(v_l, tables, x.dtype), n_rep)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kr).astype(jnp.float32)
        scores = scores / np.sqrt(cfg.head_dim)
        scores = scores + jnp.where(kv_mask, 0.0, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, vr)
        o = o.transpose(0, 2, 1, 3).reshape(b, k, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(cfg, lp, x)
        return x, (k_l, v_l)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    logits = _lm_head(cfg, params, x)  # [B, K, V]
    return {"k": new_k, "v": new_v}, logits


@partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def decode_step_blocked(cfg: LlamaConfig, params, cache, tables, tokens,
                        positions, write_mask):
    cache, logits = _multi_token_impl_blocked(
        cfg, params, cache, tables, tokens[:, None], positions, write_mask)
    return cache, logits[:, 0]


@partial(jax.jit, static_argnums=(0, 10, 11), donate_argnums=(2,))
def decode_burst_blocked(cfg: LlamaConfig, params, cache, tables, token0,
                         positions0, write_mask, temps, top_ps, key,
                         steps: int, need_top_p: bool = True):
    """Blocked-cache decode_burst: the engine pre-allocates blocks
    covering positions0+steps for every active slot before dispatch."""

    def step(carry, j):
        c, tok, pos = carry
        c, logits = _multi_token_impl_blocked(
            cfg, params, c, tables, tok[:, None], pos, write_mask)
        nxt = sample_tokens(logits[:, 0].astype(jnp.float32), temps,
                            top_ps, 0, jax.random.fold_in(key, j),
                            need_top_p).astype(jnp.int32)
        return (c, nxt, pos + 1), nxt

    (cache, _, _), toks = lax.scan(step, (cache, token0, positions0),
                                   jnp.arange(steps))
    return cache, toks


@partial(jax.jit, donate_argnums=(0,))
def copy_blocks(cache, src_blocks, dst_blocks):
    """Copy pool blocks src[i] → dst[i], all layers (prefix adoption in
    blocked mode — content copy; block sharing would need refcounts the
    preemption path doesn't justify yet)."""
    return {
        "k": cache["k"].at[:, dst_blocks].set(cache["k"][:, src_blocks]),
        "v": cache["v"].at[:, dst_blocks].set(cache["v"][:, src_blocks]),
    }


@partial(jax.jit, static_argnums=(3, 5))
def sample_tokens(logits, temps, top_ps, top_k: int, key,
                  need_top_p: bool = True):
    """logits [B, V] fp32; temps/top_ps [B]. Greedy where temp == 0.

    ``need_top_p=False`` (static) skips the vocab-wide argsort + cumsum of
    nucleus filtering — with top_p == 1.0 the filter keeps every token
    anyway (cum − p < 1 holds for all p > 0), and the sort over V=128k per
    step is BY FAR the most expensive op in the sampler (it dwarfs greedy
    argmax and even rivals a 1B decode forward). The engine passes it
    per-batch: only when some active request actually sets top_p < 1."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, NEG_INF, scaled)
    if need_top_p:
        # top-p: keep the smallest prefix of sorted probs with cumsum <= p
        sorted_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sorted_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_ps[:, None]  # always keep the first
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], sorted_idx].set(keep_sorted)
        masked = jnp.where(keep, scaled, NEG_INF)
    else:
        masked = scaled
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled)


@dataclass
class GenerationRequest:
    request_id: str
    prompt_ids: list[int]
    sampling: SamplingParams
    out_tokens: list[int] = field(default_factory=list)
    stream_queue: queue.Queue | None = None
    done: threading.Event = field(default_factory=threading.Event)
    error: str | None = None
    finish_reason: str | None = None
    next_pos: int = 0  # position the next token will occupy; <0 = prefilling
    prefilled_len: int = 0  # prompt tokens already in the KV cache
    preloaded: tuple | None = None  # (kv_k, kv_v, first_token) P/D import
    last_slot: int = -1  # slot the request last occupied (KV export)
    hold_slot: bool = False  # keep the slot (and its KV) after finishing
    draft_len: int = 0  # draft-cache positions filled (speculative decoding)
    draft_fail_count: int = 0  # consecutive draft catch-up failures
    spec_disabled: bool = False  # excluded from speculation (see _spec_decode)
    arrival_seq: int = 0  # admission order; blocked-KV preemption evicts newest
    prefill_gen: int = 0  # bumped on preemption: stale deferred fetches no-op
    # Request tracing: the submitter's propagated context (None = untraced)
    # plus the phase timestamps the scheduler thread stamps engine spans
    # from (engine.queue / engine.prefill / engine.decode — the TTFT
    # breakdown). kv_imported marks a P/D hand-off continuation.
    trace_ctx: dict | None = None
    submit_ts: float = 0.0
    admit_ts: float = 0.0
    first_token_ts: float = 0.0
    kv_imported: bool = False


@dataclass
class GenerationResult:
    request_id: str
    prompt_ids: list[int]
    token_ids: list[int]
    text: str
    finish_reason: str


@guarded_by("_submit_lock", "_requests")
class LLMEngine:
    """The continuous-batching engine. Thread-safe: ``generate``/``submit``
    may be called concurrently (e.g. from serve replica threads); one
    background scheduler thread owns the device state."""

    def __init__(self, config: LLMConfig, params: Any = None):
        self.config = config
        self.model_cfg = config.model_config()
        self.tokenizer = get_tokenizer(config.tokenizer)
        self.max_slots = config.max_num_seqs

        if params is None and config.checkpoint_path:
            import os as _os

            if _os.path.isfile(_os.path.join(config.checkpoint_path,
                                             "config.json")):
                # HuggingFace checkpoint directory: geometry comes from the
                # checkpoint itself (reference: ray.llm passes HF ids to
                # vLLM; here llm/hf.py converts weights directly).
                from ray_tpu.llm.hf import convert_hf_llama

                self.model_cfg, params = convert_hf_llama(
                    config.checkpoint_path, dtype=config.dtype)
            else:
                params = _load_checkpoint(config.checkpoint_path)
        # Validate against the FINAL geometry — an HF checkpoint replaces
        # config.model's placeholder, and its (usually larger) vocab is
        # what the tokenizer must fit in.
        self.max_seq = config.max_seq_len or self.model_cfg.max_seq_len
        if self.tokenizer.vocab_size > self.model_cfg.vocab_size:
            raise ValueError("tokenizer vocab exceeds model vocab")
        if params is None:
            params = init_params(self.model_cfg,
                                 jax.random.PRNGKey(config.seed))
        self.params = params
        self.mesh = None
        if config.tensor_parallel_size > 1:
            self._shard_for_tp(config.tensor_parallel_size)
        # KV layout: dense [slots, max_seq] lines, or the block pool (see
        # the blocked-cache section above and LLMConfig.kv_block_size).
        self.block_size = int(getattr(config, "kv_block_size", 0) or 0)
        self.blocked = self.block_size > 0
        if self.blocked:
            if config.speculative_model is not None:
                raise ValueError(
                    "speculative decoding requires the dense KV layout "
                    "(kv_block_size=0)")
            if self.block_size & (self.block_size - 1):
                raise ValueError("kv_block_size must be a power of two")
            if self.max_seq % self.block_size:
                raise ValueError(
                    "max_seq_len must be a multiple of kv_block_size")
            self.blocks_per_slot = self.max_seq // self.block_size
            self.num_blocks = int(
                getattr(config, "kv_num_blocks", 0)
                or (self.max_slots * self.blocks_per_slot + 1) // 2)
            self.cache = init_kv_cache_blocked(
                self.model_cfg, self.num_blocks, self.block_size)
            self._tables = np.zeros(
                (self.max_slots, self.blocks_per_slot), np.int32)
            self._free_blocks: list[int] = list(range(self.num_blocks))
            self._slot_nblk = [0] * self.max_slots
            self.preemptions = 0
        else:
            self.cache = init_kv_cache(self.model_cfg, self.max_slots,
                                       self.max_seq)

        # Speculative decoding: draft model + its own KV cache. The draft
        # must share the tokenizer's vocab space with the target.
        self.draft_cfg = config.draft_model_config()
        self.spec_k = max(1, int(config.speculative_tokens))
        self.draft_params = None
        self.draft_cache = None
        self.spec_ticks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        if self.draft_cfg is not None:
            if self.draft_cfg.vocab_size != self.model_cfg.vocab_size:
                raise ValueError(
                    "speculative draft must share the target's vocab "
                    f"({self.draft_cfg.vocab_size} != "
                    f"{self.model_cfg.vocab_size})")
            dp = None
            if config.speculative_checkpoint_path:
                dp = _load_checkpoint(config.speculative_checkpoint_path)
            if dp is None:
                dp = init_params(self.draft_cfg,
                                 jax.random.PRNGKey(config.seed + 7))
            self.draft_params = dp
            self.draft_cache = init_kv_cache(self.draft_cfg,
                                             self.max_slots, self.max_seq)

        self._slots: dict[int, GenerationRequest | None] = {
            i: None for i in range(self.max_slots)}
        # Prefix KV reuse (reference: vLLM automatic prefix caching +
        # routing_policies/prefix_aware/ — the serve router already sends
        # shared-prefix requests to the same replica; here the engine makes
        # the shared prefill actually free). Donor registry:
        # - _prefix_live: slot -> prompt tokens, prefill COMPLETE, request
        #   still running (adoption copies the line to the new slot).
        # - _prefix_cached: retired slot -> (tokens, last_use); the slot is
        #   unoccupied but its KV is intact — an exact/prefix re-hit admits
        #   straight into it with zero copy; unrelated admits evict LRU.
        self._prefix_live: dict[int, tuple[int, ...]] = {}
        self._prefix_cached: dict[int, tuple[tuple[int, ...], float]] = {}
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        # KV-block-aware routing: chain hashes of the cached prefixes are
        # published to the serve router (serve/prefix.py) so shared-prefix
        # bursts land on the replica already holding the blocks. The hash
        # cache is keyed by the prompt tuple and pruned to the live donor
        # set on every publish.
        self.prefix_block = int(getattr(config, "prefix_block_tokens", 32)
                                or 0)
        self._prefix_hash_cache: dict[tuple, tuple[int, ...]] = {}
        self._cache_gen = 0  # bumped when a device failure rebuilds the cache
        self._prefill_rr = -1  # last slot that ran a prefill chunk
        self._waiting: queue.Queue[GenerationRequest] = queue.Queue()
        # Held slots returned by release_slot (user threads); the
        # scheduler thread frees + retires them at tick start — slot and
        # prefix-cache registries have a single mutating thread.
        self._released: queue.Queue[GenerationRequest] = queue.Queue()
        # Preempted (blocked-KV) requests re-admit ahead of the queue.
        self._preempted: deque[GenerationRequest] = deque()
        self._arrival_seq = 0
        self._requests: dict[str, GenerationRequest] = {}
        # Serve replicas submit from max_concurrency pool threads: the
        # arrival counter and request-table insert must not interleave
        # (rtlint R1 — the same non-atomic += class as the PR-12 seq_no
        # bug). The scheduler thread takes it only for its table pop.
        self._submit_lock = threading.Lock()
        self._rng_key = jax.random.PRNGKey(config.seed + 1)
        # Pipelined decode: (active snapshot, burst, device tokens) of a
        # chained burst awaiting resolution at the next tick's start.
        self._pending_burst = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ---- public API ----

    def submit(self, prompt: str | list[int],
               sampling: SamplingParams | None = None,
               stream: bool = False) -> GenerationRequest:
        sampling = sampling or SamplingParams()
        ids = (self.tokenizer.encode(prompt) if isinstance(prompt, str)
               else list(prompt))
        ids = ids[: self.max_seq - 1]
        req = GenerationRequest(
            request_id=uuid.uuid4().hex[:12], prompt_ids=ids,
            sampling=sampling,
            stream_queue=queue.Queue() if stream else None)
        # Capture the submitter's trace context while its thread-local is
        # live: the scheduler thread stamps the engine phase spans onto
        # the REQUEST's trace from a thread that never entered it.
        req.trace_ctx = tracing.inject() if tracing.current_context() \
            else None
        req.submit_ts = time.time()
        with self._submit_lock:
            self._arrival_seq += 1
            req.arrival_seq = self._arrival_seq
            self._requests[req.request_id] = req
        self._waiting.put(req)
        self._work.set()
        return req

    def generate(self, prompt: str | list[int],
                 sampling: SamplingParams | None = None,
                 timeout: float = 300.0) -> GenerationResult:
        req = self.submit(prompt, sampling)
        if not req.done.wait(timeout):
            raise TimeoutError(f"generation {req.request_id} timed out")
        if req.error:
            raise RuntimeError(req.error)
        return self._result(req)

    # -- prefill/decode disaggregation (reference:
    #    serving_patterns/prefill_decode/pd_server.py + kv_transfer/ — a
    #    prefill engine computes the prompt's KV once, ships it, and a
    #    decode engine continues token generation from it) --

    def prefill_only(self, prompt: str | list[int],
                     sampling: SamplingParams | None = None) -> dict:
        """Run ONLY the prompt prefill; return the KV slice + first sampled
        token for hand-off to a decode engine."""
        if self.blocked:
            raise ValueError(
                "prefill/decode disaggregation exports dense KV lines; "
                "run the prefill engine with kv_block_size=0")
        sampling = sampling or SamplingParams()
        ids = (self.tokenizer.encode(prompt) if isinstance(prompt, str)
               else list(prompt))
        ids = ids[: self.max_seq - 1]
        req = GenerationRequest(
            request_id=uuid.uuid4().hex[:12], prompt_ids=ids,
            sampling=replace(sampling, max_tokens=1), hold_slot=True)
        req.trace_ctx = tracing.inject() if tracing.current_context() \
            else None
        req.submit_ts = time.time()
        with self._submit_lock:
            self._requests[req.request_id] = req
        self._waiting.put(req)
        self._work.set()
        try:
            if not req.done.wait(120):
                raise TimeoutError("prefill timed out")
            # Capture the cache reference + generation BEFORE the error
            # check: if a device failure rebuilds the cache mid-export, the
            # gen re-check below turns a silent all-zero export into an
            # error (reading the old donated cache raises on its own).
            cache, gen = self.cache, self._cache_gen
            if req.error:
                raise RuntimeError(req.error)
            p = len(ids)
            # hold_slot kept the slot reserved so no other admit overwrote
            # the KV lines between finish and this export.
            slot = req.last_slot
            kv_k = np.asarray(cache["k"][:, slot, :, :p, :])
            kv_v = np.asarray(cache["v"][:, slot, :, :p, :])
            if self._cache_gen != gen or req.error:
                raise RuntimeError(
                    req.error or "KV cache lost during prefill export")
        finally:
            # On timeout the request may still be running: dropping
            # hold_slot lets its eventual _finish free the slot — orphaned
            # holds would leak slots until the engine deadlocks.
            req.hold_slot = False
            self.release_slot(req)
        return {"prompt_ids": ids, "kv_k": kv_k, "kv_v": kv_v,
                "first_token": req.out_tokens[0],
                "finish_reason": req.finish_reason}

    def release_slot(self, req: GenerationRequest) -> None:
        """Return a ``hold_slot`` reservation (prefill_only's export is
        done). Handed to the scheduler thread: it frees the slot and — the
        hand-off's KV line being a fully-prefilled prompt — RETIRES it as
        a cached prefix instead of discarding it, so a dedicated prefill
        engine accumulates the prefix cache its replica publishes for
        KV-block-aware routing (a shared-prefix burst then prefills only
        the tail). Freeing from this (user) thread raced the scheduler's
        admit: retire-then-clear could in-place-adopt a slot mid-release,
        clear-then-retire could mark a freshly re-admitted slot cached."""
        self._released.put(req)
        self._work.set()

    def _process_releases(self) -> None:
        """Scheduler-thread half of release_slot."""
        while True:
            try:
                req = self._released.get_nowait()
            except queue.Empty:
                return
            if req.finish_reason is None and not req.error:
                # Export timed out while the prefill still runs: its
                # _finish (hold_slot was dropped) frees the slot — freeing
                # here would hand a mid-prefill slot to the next admit.
                continue
            for slot, r in self._slots.items():
                if r is req:
                    self._slots[slot] = None
                    self._prefix_live.pop(slot, None)
                    if self.blocked:
                        self._free_slot_blocks(slot)
                    elif (req.finish_reason not in (None, "error")
                          and not req.error):
                        # Clean completed prefill: the slot's KV holds
                        # exactly req.prompt_ids' prefix — retire it.
                        self._prefix_cached[slot] = (
                            tuple(req.prompt_ids), time.monotonic())

    def submit_prefilled(self, payload: dict,
                         sampling: SamplingParams | None = None,
                         stream: bool = False) -> GenerationRequest:
        """Continue decoding from a shipped prefill (KV import)."""
        if self.blocked:
            raise ValueError(
                "KV import writes dense KV lines; run the decode engine "
                "with kv_block_size=0")
        sampling = sampling or SamplingParams()
        req = GenerationRequest(
            request_id=uuid.uuid4().hex[:12],
            prompt_ids=list(payload["prompt_ids"]), sampling=sampling,
            stream_queue=queue.Queue() if stream else None)
        req.preloaded = (np.asarray(payload["kv_k"]),
                         np.asarray(payload["kv_v"]),
                         int(payload["first_token"]))
        req.trace_ctx = tracing.inject() if tracing.current_context() \
            else None
        req.submit_ts = time.time()
        req.kv_imported = True
        with self._submit_lock:
            self._requests[req.request_id] = req
        self._waiting.put(req)
        self._work.set()
        return req

    def generate_stream(self, prompt: str | list[int],
                        sampling: SamplingParams | None = None):
        """Yields decoded text fragments as tokens arrive."""
        req = self.submit(prompt, sampling, stream=True)
        while True:
            item = req.stream_queue.get()
            if item is None:
                break
            yield self.tokenizer.decode([item])
        if req.error:
            raise RuntimeError(req.error)

    def shutdown(self) -> None:
        self._stop.set()
        self._work.set()
        self._thread.join(timeout=5)

    def prefix_block_hashes(self) -> tuple[int, ...]:
        """Chain hashes (serve/prefix.py) of every prompt prefix whose KV
        this engine currently holds — live donors plus retired cached
        slots. This is what the replica publishes to the serve router for
        KV-block-aware routing. Safe from any thread: the registries are
        snapshotted (the scheduler thread mutates them concurrently) and
        the per-prompt hash cache swap is idempotent."""
        if self.prefix_block <= 0:
            return ()
        from ray_tpu.serve.prefix import block_hashes

        prefixes = list(self._prefix_live.values())
        prefixes += [toks for toks, _ in list(self._prefix_cached.values())]
        cache = self._prefix_hash_cache
        fresh: dict[tuple, tuple[int, ...]] = {}
        out: set[int] = set()
        for toks in prefixes:
            h = cache.get(toks)
            if h is None:
                h = block_hashes(toks, self.prefix_block)
            fresh[toks] = h
            out.update(h)
        self._prefix_hash_cache = fresh  # prune evicted prefixes
        return tuple(sorted(out))

    def router_prefix_blocks(self) -> dict | None:
        """The publication payload serve replicas answer router_meta()
        with (one definition of the contract for every deployment type:
        LLMServer and PrefillServer both delegate here). None when
        publication is disabled — the controller then stops polling."""
        if self.prefix_block <= 0:
            return None
        return {"blocks": list(self.prefix_block_hashes()),
                "block": self.prefix_block}

    def stats(self) -> dict:
        active = sum(1 for r in self._slots.values() if r is not None)
        out = {"active": active, "waiting": self._waiting.qsize(),
               "slots": self.max_slots,
               "prefix_hits": self.prefix_hits,
               "prefix_tokens_saved": self.prefix_tokens_saved,
               "prefix_cached_slots": len(self._prefix_cached),
               "prefix_block": self.prefix_block}
        if self.blocked:
            out["kv_blocks_total"] = self.num_blocks
            out["kv_blocks_free"] = len(self._free_blocks)
            out["kv_block_size"] = self.block_size
            out["preemptions"] = self.preemptions
        if self.draft_cfg is not None:
            out["spec_ticks"] = self.spec_ticks
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
            out["spec_acceptance"] = (
                round(self.spec_accepted / self.spec_proposed, 3)
                if self.spec_proposed else 0.0)
        return out

    # ---- scheduler ----

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self._tick()
            except Exception:  # noqa: BLE001 - one bad request must not
                # kill the scheduler thread (every queued request would
                # hang to its timeout). _prefill_step/_decode fail the
                # offending requests where attributable; anything that
                # still escapes is logged and backed off, never hot-spun.
                logger.exception("LLMEngine scheduler tick failed")
                worked = False
            if not worked:
                self._work.wait(timeout=0.02)
                self._work.clear()
        # Drain a chained burst so its requests get their final tokens
        # instead of hanging to their timeouts.
        try:
            self._resolve_pending_burst()
        except Exception:  # noqa: BLE001 - shutdown path
            pass

    def _tick(self) -> bool:
        """One scheduler step: a bounded budget of prefill chunks (their
        first-token fetches deferred), then one decode batch over the
        decoding slots. Chunking + the per-tick budget stop a long prompt
        from head-of-line-blocking every active decode (reference shape:
        vLLM chunked prefill scheduling); deferring the prefill fetches
        until the decode work is queued means the whole tick pays ONE
        host⇄device roundtrip however many prefills it ran."""
        # Admit into CURRENTLY-empty slots and dispatch their prefill
        # chunks BEFORE blocking on the pipelined burst's fetch: the
        # prefill rides the device queue behind the in-flight burst and
        # its first token is ready ~one prefill after that burst, instead
        # of TTFT paying a full extra burst+chain. This is safe because a
        # slot that is empty now was freed at or before the pending
        # burst's dispatch, so that burst's write mask provably excludes
        # it — only slots freed BY the pending resolve (mid-burst
        # finishes) must wait for it, and those are still occupied here.
        self._process_releases()
        worked = self._admit()
        deferred: list = []
        try:
            return self._tick_inner(deferred) or worked
        finally:
            # An exception between a prefill dispatch and its resolution
            # must not strand the deferred first-token fetches — the
            # requests would report prefilled but never start decoding
            # (hang to client timeout). Whatever survived, resolve it.
            self._resolve_prefills(deferred)

    def _tick_inner(self, deferred: list) -> bool:
        worked = False
        # Per-PASS chunk budget: the tick has two admission passes (before
        # and after resolving the pipelined burst) and each gets a full
        # prefill_chunks_per_tick. A shared budget was measured ~25%
        # worse p50 TTFT at c8: completions arrive in bursts, and an
        # arrival landing after the resolve must not wait a whole
        # burst+chain because the pre-resolve pass spent the budget.
        budget = max(1, int(getattr(self.config,
                                    "prefill_chunks_per_tick", 1) or 1))
        spent = 0
        while spent < budget and self._prefill_step(deferred):
            spent += 1
            worked = True
        # Resolve the pipelined burst next: its emissions may finish
        # requests and free slots for the SECOND admission pass below.
        # (Poll-admission during the chain fetch — admitting while
        # toks_dev computes — was measured WORSE end-to-end on the
        # tunneled chip: busy-polling starves the same single core that
        # runs the HTTP/router/SSE threads: p50 366 -> 472 ms, 216 -> 194
        # tok/s. The blocking fetch it replaced is also this box's yield.)
        worked = self._resolve_pending_burst() or worked
        worked = self._admit() or worked
        spent = 0
        while spent < budget and self._prefill_step(deferred):
            spent += 1
            worked = True
        decoding = {s: r for s, r in self._slots.items()
                    if r is not None and r.next_pos >= 0
                    and not r.done.is_set()}
        if decoding and self.draft_params is not None:
            # Speculative path serves greedy requests with spec headroom;
            # the rest (stochastic sampling, near end-of-cache) ride the
            # normal decode in the same tick.
            spec = {s: r for s, r in decoding.items()
                    if r.sampling.temperature <= 0.0
                    and r.next_pos + self.spec_k + 1 < self.max_seq}
            rest = {s: r for s, r in decoding.items() if s not in spec}
            if spec:
                self._spec_decode(spec)
                worked = True
            if rest:
                self._decode(rest)
                worked = True
            return worked
        if decoding:
            self._decode(decoding)
            worked = True
        return worked

    def _resolve_prefills(self, deferred: list) -> None:
        """Fetch the deferred first tokens (dispatched in _prefill_step)
        and start those requests decoding. Runs AFTER the tick's decode
        dispatch so the fetch overlaps the queued device work."""
        for req, gen, out in deferred:
            if req.done.is_set():  # failed meanwhile (device recovery)
                continue
            if gen != req.prefill_gen:
                # Preempted (and possibly re-admitted) after this fetch was
                # dispatched: the token belongs to a KV state that no
                # longer exists — emitting it would duplicate the first
                # token of the re-prefill.
                continue
            try:
                tok = int(np.asarray(out)[0])
            except Exception as e:  # noqa: BLE001 - async dispatch error
                # surfaces at materialization; engine state is suspect.
                logger.exception("deferred prefill sample failed for %s",
                                 req.request_id)
                self._recover_device_failure(f"prefill failed: {e!r}")
                return
            req.next_pos = len(req.prompt_ids)
            self._emit(req, tok)

    # Minimum adopted-prefix length that justifies a cross-slot KV copy
    # (the copy moves whole cache lines; tiny prefixes aren't worth it).
    PREFIX_COPY_MIN = 16

    # Decode-burst cap while a slot is mid-prefill (see _burst_len):
    # bounds how long the next prefill chunk waits behind decode work
    # while keeping most of the burst's dispatch amortization.
    PREFILL_PRIORITY_BURST = 8

    def _admit(self) -> bool:
        """Move waiting requests into unoccupied slots (prefill starts on
        subsequent ticks), adopting cached prompt prefixes when a donor
        slot shares one (vLLM-APC semantics: the final prompt token is
        always recomputed so its logits seed decoding)."""
        admitted = False
        while any(o is None for o in self._slots.values()):
            try:
                req = self._next_waiting()
            except queue.Empty:
                break
            req.admit_ts = time.time()
            if req.preloaded is not None:
                slot = self._take_slot()
                try:
                    self._admit_prefilled(req, slot)
                except Exception as e:  # noqa: BLE001 - bad KV payload
                    self._slots[slot] = None
                    self._fail(req, f"KV import failed: {e!r}")
                admitted = True
                continue
            donor, adopt, retired = self._best_prefix(req.prompt_ids)
            req.prefilled_len = 0
            if self.blocked:
                # Block-pool prefix adoption: whole-block content copy
                # from a LIVE donor (no retired-slot cache — finished
                # requests release their blocks back to the pool).
                slot = self._take_slot()
                adopt = (adopt // self.block_size) * self.block_size
                if (donor is not None and not retired
                        and adopt >= max(self.PREFIX_COPY_MIN,
                                         self.block_size)
                        # preempt=False: with eviction allowed the victim
                        # could be the DONOR, whose freed blocks would be
                        # re-issued as the copy's destination while its
                        # table row still points at them.
                        and self._ensure_blocks(slot, adopt - 1,
                                                preempt=False)):
                    nb = adopt // self.block_size
                    src = jnp.asarray(self._tables[donor, :nb])
                    dst = jnp.asarray(self._tables[slot, :nb])
                    try:
                        self.cache = copy_blocks(self.cache, src, dst)
                        req.prefilled_len = adopt
                        self.prefix_hits += 1
                        self.prefix_tokens_saved += adopt
                    except Exception as e:  # noqa: BLE001 - donated cache
                        logger.exception("block prefix copy failed")
                        self._recover_device_failure(
                            f"prefix copy failed: {e!r}")
                        req.prefilled_len = 0
                req.next_pos = -1
                req.last_slot = slot
                self._slots[slot] = req
                admitted = True
                continue
            if donor is not None and adopt < self.PREFIX_COPY_MIN:
                # Trivial LCP (e.g. a shared few-token template label):
                # not worth a copy, and NEVER worth destroying a donor.
                donor = None
            if retired and donor is not None and \
                    adopt * 2 >= len(self._prefix_cached[donor][0]):
                # Zero-copy: admit straight into the retired slot whose KV
                # already holds the prefix — only when the new prompt
                # consumes most of it. An in-place adopt OVERWRITES the
                # donor: taking a 1000-token cached line for a 20-token
                # LCP (hot prompts sharing a template label) was measured
                # pinning the whole cache at ONE entry under prefix-skewed
                # load — every admit stole the same slot while fresh
                # slots idled.
                slot = donor
                self._prefix_cached.pop(slot, None)
                req.prefilled_len = adopt
                self.prefix_hits += 1
                self.prefix_tokens_saved += adopt
            else:
                slot = self._take_slot()
                if donor is not None and slot == donor:
                    # LRU eviction handed us the donor itself (no fresh
                    # slot): its KV line is already in place — in-place
                    # adoption after all, minus the copy.
                    req.prefilled_len = adopt
                    self.prefix_hits += 1
                    self.prefix_tokens_saved += adopt
                elif donor is not None:
                    # Content copy from the donor line (live OR retired —
                    # both hold intact KV) into the fresh slot, preserving
                    # the donor for future siblings.
                    try:
                        self.cache = copy_prefix_kv(
                            self.model_cfg, self.cache, jnp.int32(donor),
                            jnp.int32(slot))
                        req.prefilled_len = adopt
                        self.prefix_hits += 1
                        self.prefix_tokens_saved += adopt
                        if donor in self._prefix_cached:
                            # Donor USED: now is when it earns its LRU
                            # refresh (stamping at _best_prefix time let
                            # guard-rejected donors dodge eviction).
                            self._prefix_cached[donor] = (
                                self._prefix_cached[donor][0],
                                time.monotonic())
                    except Exception as e:  # noqa: BLE001
                        # copy_prefix_kv DONATES the cache: a failed
                        # dispatch consumed its buffers, so this is a
                        # device-failure event, not a per-request fallback
                        # — rebuild, then admit this request cold.
                        logger.exception("prefix copy failed")
                        self._recover_device_failure(
                            f"prefix copy failed: {e!r}")
                        req.prefilled_len = 0
            # next_pos < 0 marks "still prefilling" (prefilled_len tracks
            # progress); _finish frees by identity.
            req.next_pos = -1
            req.last_slot = slot
            self._slots[slot] = req
            admitted = True
        return admitted

    # ---- blocked-KV pool accounting (scheduler thread only) ----

    def _ensure_blocks(self, slot: int, upto_pos: int,
                       preempt: bool = True) -> bool:
        """Grow ``slot``'s block table to cover position ``upto_pos``,
        preempting the newest other request on pool exhaustion (unless
        ``preempt`` is False — e.g. a speculative chained burst is never
        worth an eviction). False if the pool cannot cover it."""
        need = min(upto_pos // self.block_size + 1, self.blocks_per_slot)
        while self._slot_nblk[slot] < need:
            if not self._free_blocks and not (
                    preempt and self._preempt_for_blocks(slot)):
                return False
            self._tables[slot, self._slot_nblk[slot]] = \
                self._free_blocks.pop()
            self._slot_nblk[slot] += 1
        return True

    def _free_slot_blocks(self, slot: int) -> None:
        n = self._slot_nblk[slot]
        if n:
            self._free_blocks.extend(int(b) for b in self._tables[slot, :n])
            self._slot_nblk[slot] = 0

    def _preempt_for_blocks(self, exclude_slot: int) -> bool:
        """Evict the NEWEST other request (vLLM preemption order: latest
        arrivals yield to earlier ones) by recompute: free its blocks and
        requeue it; on readmission its prompt+generated tokens re-prefill
        and decoding continues — emitted tokens are never re-emitted."""
        victims = [(s, r) for s, r in self._slots.items()
                   if r is not None and s != exclude_slot
                   and not r.done.is_set() and not r.hold_slot
                   and r.preloaded is None]
        if not victims:
            return False
        # An in-flight chained burst still emits for its snapshot: resolve
        # it first so a preempted request can't receive its tokens.
        self._resolve_pending_burst()
        if self._free_blocks:
            return True  # the resolve's finishes freed enough — no eviction
        victims = [(s, r) for s, r in victims
                   if self._slots.get(s) is r and not r.done.is_set()]
        if not victims:
            return False
        slot, req = max(victims, key=lambda sr: sr[1].arrival_seq)
        self._preempt_slot(slot, req)
        return True

    def _preempt_slot(self, slot: int, req: "GenerationRequest") -> None:
        self.preemptions += 1
        self._prefix_live.pop(slot, None)
        self._slots[slot] = None
        self._free_slot_blocks(slot)
        req.prompt_ids = list(req.prompt_ids) + list(req.out_tokens)
        req.prefilled_len = 0
        req.next_pos = -1
        req.prefill_gen += 1  # invalidate in-flight deferred fetches
        if len(req.prompt_ids) >= self.max_seq:
            self._finish(req, "length")
        else:
            self._preempted.append(req)

    def _ensure_decode_blocks(self, active: dict, burst: int) -> dict:
        """Cover positions next_pos..next_pos+burst-1 for every active
        slot before a decode dispatch; a slot the pool cannot cover (even
        after evicting newer requests) is itself preempted."""
        out = {}
        for slot, req in active.items():
            if self._slots.get(slot) is not req or req.done.is_set():
                continue  # evicted by an earlier slot's ensure
            if self._ensure_blocks(slot, req.next_pos + burst - 1):
                out[slot] = req
            else:
                self._preempt_slot(slot, req)
        # A LATER slot's ensure may have evicted a request accepted above —
        # dispatching it anyway would write through its stale table into
        # blocks the pool already re-issued. Re-filter against live slots.
        return {s: r for s, r in out.items()
                if self._slots.get(s) is r and not r.done.is_set()}

    def _next_waiting(self) -> "GenerationRequest":
        """Preempted requests re-admit ahead of fresh arrivals."""
        if self._preempted:
            return self._preempted.popleft()
        return self._waiting.get_nowait()

    def _take_slot(self) -> int:
        """An unoccupied slot: prefer one with no cached prefix; otherwise
        evict the least-recently-used prefix entry."""
        fresh = [s for s, o in self._slots.items()
                 if o is None and s not in self._prefix_cached]
        if fresh:
            return fresh[0]
        slot = min((s for s, o in self._slots.items() if o is None),
                   key=lambda s: self._prefix_cached.get(s, ((), 0.0))[1])
        self._prefix_cached.pop(slot, None)
        return slot

    def _best_prefix(self, prompt_ids: list[int]):
        """(donor_slot, usable_prefix_len, donor_is_retired) — longest
        common prefix across donors, capped at len(prompt)-1. Retired
        donors win ties (adoption is zero-copy)."""
        cap = len(prompt_ids) - 1
        best_slot, best_p, best_retired = None, 0, False
        if cap <= 0:
            return best_slot, best_p, best_retired
        # Both registries are mutated only on this (scheduler) thread —
        # release_slot hands frees over via the _released queue — but
        # user threads READ them (prefix_block_hashes), so keep the
        # snapshot-iterate discipline for the shared-read invariant.
        # LRU re-stamping of a retired donor happens in _admit, and ONLY
        # when the donor is actually used: stamping here shielded lines
        # the admission guards then rejected (e.g. a trivial template-
        # label LCP) from eviction, starving genuinely hot entries.
        for slot, toks in list(self._prefix_live.items()):
            p = _lcp(prompt_ids, toks, cap)
            if p > best_p:
                best_slot, best_p, best_retired = slot, p, False
        for slot, (toks, _) in list(self._prefix_cached.items()):
            p = _lcp(prompt_ids, toks, cap)
            if p > best_p or (p == best_p and p > 0 and not best_retired):
                best_slot, best_p, best_retired = slot, p, True
        return best_slot, best_p, best_retired

    def _admit_prefilled(self, req: GenerationRequest, slot: int) -> None:
        """KV import: write the shipped prefill into this slot and enter
        decode directly (reference: kv_transfer connectors on the decode
        engine side)."""
        import jax.numpy as jnp
        from jax import lax

        kv_k, kv_v, first_token = req.preloaded
        want = (self.model_cfg.num_layers, self.model_cfg.num_kv_heads,
                self.model_cfg.head_dim)
        got = (kv_k.shape[0], kv_k.shape[1], kv_k.shape[3])
        p = kv_k.shape[2]
        if got != want or p > self.max_seq or kv_v.shape != kv_k.shape:
            raise ValueError(
                f"payload KV shape {kv_k.shape} incompatible with this "
                f"engine (layers/kv_heads/head_dim {want}, max_seq "
                f"{self.max_seq})")
        self.cache["k"] = lax.dynamic_update_slice(
            self.cache["k"],
            jnp.asarray(kv_k, self.cache["k"].dtype)[:, None],
            (0, slot, 0, 0, 0))
        self.cache["v"] = lax.dynamic_update_slice(
            self.cache["v"],
            jnp.asarray(kv_v, self.cache["v"].dtype)[:, None],
            (0, slot, 0, 0, 0))
        req.preloaded = None
        req.next_pos = p
        req.last_slot = slot
        self._slots[slot] = req
        self._prefix_live[slot] = tuple(req.prompt_ids)  # imported KV = donor
        self._emit(req, first_token)

    def _prefill_step(self, deferred: list) -> bool:
        """Run ONE chunk of ONE prefilling request, rotating across slots so
        concurrent long prompts interleave chunks (true round-robin — a
        lowest-slot rescan would monopolize prefill for one prompt).

        A final chunk's first-token sample is DISPATCHED but not fetched:
        (req, device_tokens) is appended to ``deferred`` for the caller to
        resolve after it has queued the tick's decode work — one
        host⇄device roundtrip per tick instead of one per prefill (the
        fetch is the expensive part on remote/tunneled devices)."""
        slots = list(self._slots.keys())
        n = len(slots)
        for i in range(n):
            slot = slots[(self._prefill_rr + 1 + i) % n]
            req = self._slots.get(slot)
            if req is None or req.next_pos >= 0:
                continue
            p = len(req.prompt_ids)
            if req.prefilled_len >= p:
                # Fully prefilled, first-token fetch still deferred this
                # tick — re-prefilling would dispatch a zero-take chunk and
                # sample (emit!) a duplicate first token.
                continue
            self._prefill_rr = slot
            bucket, take = self._chunk_bucket(req.prefilled_len,
                                              p - req.prefilled_len)
            toks = np.zeros((bucket,), np.int32)
            toks[:take] = req.prompt_ids[req.prefilled_len:
                                         req.prefilled_len + take]
            if self.blocked and not self._ensure_blocks(
                    slot, req.prefilled_len + bucket - 1):
                self._slots[slot] = None
                self._free_slot_blocks(slot)
                self._fail(req, "KV block pool exhausted "
                                f"({self.num_blocks} blocks x "
                                f"{self.block_size} tokens)")
                return True
            try:
                if self.blocked:
                    self.cache, logits = prefill_chunk_blocked(
                        self.model_cfg, self.params, self.cache,
                        jnp.asarray(self._tables[slot]), jnp.asarray(toks),
                        jnp.int32(req.prefilled_len), jnp.int32(p))
                else:
                    self.cache, logits = prefill_chunk(
                        self.model_cfg, self.params, self.cache,
                        jnp.asarray(toks), jnp.int32(req.prefilled_len),
                        jnp.int32(p), jnp.int32(slot))
                req.prefilled_len += take
                if req.prefilled_len >= p:  # final chunk: sample 1st token
                    # The slot now holds the full prompt's KV: it becomes a
                    # prefix donor for later shared-prefix requests.
                    self._prefix_live[slot] = tuple(req.prompt_ids)
                    out = self._sample_dispatch(logits[None], [req])
                    deferred.append((req, req.prefill_gen, out))
            except Exception as e:  # noqa: BLE001 - e.g. OOM on long prompt
                logger.exception("prefill failed for %s", req.request_id)
                self._recover_device_failure(f"prefill failed: {e!r}")
            return True
        return False

    def _recover_device_failure(self, err: str) -> None:
        """After a failed prefill/decode dispatch the KV cache is gone —
        prefill_chunk/decode_step donate it (donate_argnums=(2,)), so its
        buffers were consumed by the very call that raised. Every slotted
        request's context lived there: fail them all, then rebuild a fresh
        cache so the engine keeps serving NEW traffic."""
        self._cache_gen += 1  # invalidates in-flight prefill_only exports
        self._pending_burst = None  # chained into the lost cache
        for req in list(self._slots.values()):
            if req is None:
                continue
            if req.done.is_set():
                # Already finished (hold_slot prefill awaiting export): its
                # waiter has the result — don't rewrite finish_reason, just
                # mark the held KV unusable so the export raises.
                req.error = err
            else:
                self._fail(req, err)
        self._slots = {i: None for i in range(self.max_slots)}
        self._prefix_live.clear()
        self._prefix_cached.clear()
        if self.blocked:
            self.cache = init_kv_cache_blocked(
                self.model_cfg, self.num_blocks, self.block_size)
            self._tables[:] = 0
            self._free_blocks = list(range(self.num_blocks))
            self._slot_nblk = [0] * self.max_slots
        else:
            self.cache = init_kv_cache(self.model_cfg, self.max_slots,
                                       self.max_seq)
        if self.draft_cfg is not None:
            # The draft cache may have been donated by the failing
            # speculative dispatch — rebuild it alongside.
            self.draft_cache = init_kv_cache(self.draft_cfg,
                                             self.max_slots, self.max_seq)

    def _burst_len(self, active: dict[int, GenerationRequest]) -> int:
        """Largest safe burst length for this decode batch. The decode
        batch is the STATIC slot array, so a request finishing mid-burst
        costs nothing extra — the host just stops emitting its tokens
        (max_tokens/EOS truncation happens in _emit) and the spare KV
        writes are overwritten on slot reuse. The only hard bound is the
        KV cache end (a burst must never write past max_seq); rounded down
        to a power of two so only {8,4,2} burst shapes ever compile.
        1 means take the classic single-step path."""
        burst = int(getattr(self.config, "decode_burst", 1) or 1)
        if burst <= 1:
            return 1
        # Prefill priority (reference shape: vLLM chunked-prefill
        # scheduling): while a slot is mid-prefill, long decode bursts
        # head-of-line-block its next chunk for burst×step_ms. Cap the
        # burst so the scheduler returns to the prefill quickly;
        # steady-state decode (no prefilling slot) keeps full bursts.
        # (Capping on a non-empty admission queue as well was measured
        # 18% WORSE end-to-end on the tunneled chip: the closed-loop
        # arrival pattern made the cap near-permanent, and with tick cost
        # ≈ RTT + work, halving the work per tick just slowed everyone.)
        if any(r is not None and r.next_pos < 0 and not r.done.is_set()
               for r in self._slots.values()):
            burst = min(burst, self.PREFILL_PRIORITY_BURST)
        budget = 0  # largest remaining token budget across the batch:
        # bounding by the MAX (not min) wastes no tail steps when every
        # request is nearly done, yet a single long request still gets
        # full-length bursts (short ones just stop emitting early).
        for req in active.values():
            if req.sampling.top_k:  # static-k sampling: single-step only
                return 1
            burst = min(burst, self.max_seq - 1 - req.next_pos)
            budget = max(budget,
                         req.sampling.max_tokens - len(req.out_tokens))
        burst = min(burst, budget)
        d = 1
        while d * 2 <= burst:
            d *= 2
        return max(d, 1)

    def _decode(self, active: dict[int, GenerationRequest]) -> bool:
        """Returns False iff a device failure wiped the engine state
        (_recover_device_failure ran) — callers mid-tick must then abandon
        the rest of the tick rather than dispatch into rebuilt caches."""
        burst = self._burst_len(active)
        if self.blocked:
            active = self._ensure_decode_blocks(active, burst)
            if not active:
                return True
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        write = np.zeros((self.max_slots,), bool)
        for slot, req in active.items():
            tokens[slot] = req.out_tokens[-1]
            positions[slot] = req.next_pos
            write[slot] = True
        if burst > 1:
            return self._decode_burst(active, burst, tokens, positions,
                                      write)
        try:
            if self.blocked:
                self.cache, logits = decode_step_blocked(
                    self.model_cfg, self.params, self.cache,
                    jnp.asarray(self._tables), jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(write))
            else:
                self.cache, logits = decode_step(
                    self.model_cfg, self.params, self.cache,
                    jnp.asarray(tokens), jnp.asarray(positions),
                    jnp.asarray(write))
        except Exception as e:  # noqa: BLE001 - cache donated & lost
            logger.exception("decode step failed (%d active)", len(active))
            self._recover_device_failure(f"decode failed: {e!r}")
            return False
        try:
            reqs = [active.get(s) for s in range(self.max_slots)]
            sampled = self._sample_one(logits, reqs)
        except Exception as e:  # noqa: BLE001 - cache survived; only this
            # batch's requests lack tokens — fail them, keep other contexts.
            logger.exception("sampling failed (%d active)", len(active))
            for req in active.values():
                self._fail(req, f"sampling failed: {e!r}")
            return True
        for slot, req in active.items():
            req.next_pos += 1
            self._emit(req, int(sampled[slot]))
        return True

    def _decode_burst(self, active: dict[int, GenerationRequest],
                      burst: int, tokens, positions, write) -> bool:
        """Emit ``burst`` tokens per active slot from one device dispatch.
        A request finishing mid-burst (EOS/stop token) simply stops
        emitting; the extra KV the device wrote past its end sits at
        positions a later slot reuse overwrites (same free-rollback
        property speculative decoding relies on).

        In steady state a SECOND burst is chained before this one's
        tokens are fetched (see _should_chain), feeding the on-device
        last token forward — the fetch roundtrip then overlaps the next
        burst's compute. The chained burst is resolved at the next tick's
        start (_resolve_pending_burst)."""
        temps = np.zeros((self.max_slots,), np.float32)
        top_ps = np.ones((self.max_slots,), np.float32)
        for slot, req in active.items():
            temps[slot] = req.sampling.temperature
            top_ps[slot] = req.sampling.top_p
        need_top_p = bool((top_ps < 1.0).any())
        self._rng_key, sub = jax.random.split(self._rng_key)
        try:
            if self.blocked:
                self.cache, toks = decode_burst_blocked(
                    self.model_cfg, self.params, self.cache,
                    jnp.asarray(self._tables), jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(write),
                    jnp.asarray(temps), jnp.asarray(top_ps), sub, burst,
                    need_top_p)
            else:
                self.cache, toks = decode_burst(
                    self.model_cfg, self.params, self.cache,
                    jnp.asarray(tokens), jnp.asarray(positions),
                    jnp.asarray(write), jnp.asarray(temps),
                    jnp.asarray(top_ps), sub, burst, need_top_p)
            chain = self._should_chain(active, burst)
            if chain and self.blocked:
                # A chain must never evict someone: skip it unless every
                # slot's blocks for the second burst are already coverable.
                chain = all(self._ensure_blocks(
                    s, r.next_pos + 2 * burst - 1, preempt=False)
                    for s, r in active.items())
            if chain:
                self._rng_key, sub2 = jax.random.split(self._rng_key)
                if self.blocked:
                    self.cache, toks2 = decode_burst_blocked(
                        self.model_cfg, self.params, self.cache,
                        jnp.asarray(self._tables), toks[burst - 1],
                        jnp.asarray(positions) + burst, jnp.asarray(write),
                        jnp.asarray(temps), jnp.asarray(top_ps), sub2,
                        burst, need_top_p)
                else:
                    self.cache, toks2 = decode_burst(
                        self.model_cfg, self.params, self.cache,
                        toks[burst - 1], jnp.asarray(positions) + burst,
                        jnp.asarray(write), jnp.asarray(temps),
                        jnp.asarray(top_ps), sub2, burst, need_top_p)
                self._pending_burst = (dict(active), burst, toks2)
            toks = np.asarray(toks)  # [burst, max_slots]
        except Exception as e:  # noqa: BLE001 - cache donated & lost
            logger.exception("burst decode failed (%d active, burst %d)",
                             len(active), burst)
            self._recover_device_failure(f"decode failed: {e!r}")
            return False
        self._emit_burst(active, burst, toks)
        return True

    def _should_chain(self, active: dict[int, GenerationRequest],
                      burst: int) -> bool:
        """Chain a second burst only when the device would otherwise sit
        idle through the fetch: steady decode (nothing waiting to admit,
        no prefilling slot, no draft model interleaving the cache), every
        slot has cache headroom for TWO bursts, and someone still needs
        more than one burst of tokens."""
        if burst <= 1 or not getattr(self.config, "decode_pipeline", False):
            return False
        if self._pending_burst is not None or self.draft_params is not None:
            return False
        if not self._waiting.empty() or self._preempted:
            return False
        for r in self._slots.values():
            if r is not None and r.next_pos < 0:
                return False  # a prefill wants the next tick
        budget = 0
        for req in active.values():
            if self.max_seq - 1 - req.next_pos < 2 * burst:
                return False
            budget = max(budget,
                         req.sampling.max_tokens - len(req.out_tokens))
        return budget > burst

    def _resolve_pending_burst(self) -> bool:
        """Fetch + emit the burst chained by the previous tick."""
        if self._pending_burst is None:
            return False
        active, burst, toks_dev = self._pending_burst
        self._pending_burst = None
        try:
            toks = np.asarray(toks_dev)
        except Exception as e:  # noqa: BLE001 - surfaces at materialization
            logger.exception("pipelined burst failed (%d slots)", len(active))
            self._recover_device_failure(f"decode failed: {e!r}")
            return True
        self._emit_burst(active, burst, toks)
        return True

    def _emit_burst(self, active, burst: int, toks) -> None:
        for j in range(burst):
            for slot, req in active.items():
                if req.done.is_set():
                    continue
                req.next_pos += 1
                self._emit(req, int(toks[j, slot]))

    def _spec_decode(self, active: dict[int, GenerationRequest]) -> None:
        """One speculative tick: draft proposes spec_k tokens per slot in
        one dispatch, the target verifies them (+ the bonus position) in
        one forward, and each slot advances by accepted+1 tokens. Greedy
        acceptance makes the output IDENTICAL to vanilla greedy decoding
        whatever the draft proposes; stale KV beyond the accepted prefix
        is masked/overwritten by position bookkeeping (free rollback)."""
        k = self.spec_k
        # Requests whose draft catch-up keeps failing are speculation-
        # disabled (bounded blast radius: one bad request must not turn
        # speculation off engine-wide forever) — plain-decode those, then
        # run the speculative tick for the rest.
        spec_active = {s: r for s, r in active.items() if not r.spec_disabled}
        plain_active = {s: r for s, r in active.items() if r.spec_disabled}
        if not spec_active:
            self._decode(active)
            return
        if plain_active and not self._decode(plain_active):
            # The plain half hit a device failure: every slot (including
            # the speculative ones) was failed and both caches rebuilt —
            # nothing valid remains for the speculative half of this tick.
            return
        active = spec_active
        # Draft catch-up: any slot whose draft cache lags (fresh prompt,
        # prefix adoption, PD import, all-k-accepted tail) prefills the
        # missing span — cheap, the draft is small by construction.
        for slot, req in active.items():
            if req.draft_len < req.next_pos and \
                    not self._draft_catch_up(slot, req):
                # The failed dispatch reset the WHOLE draft state (cache
                # rebuilt, every draft_len zeroed) — slots that caught up
                # earlier this tick are invalid too. Plain-decode the whole
                # tick; catch-up re-runs for everyone next tick (minus any
                # request _draft_catch_up just speculation-disabled).
                self._decode(active)
                return
        token0 = np.zeros((self.max_slots,), np.int32)
        pos0 = np.zeros((self.max_slots,), np.int32)
        write = np.zeros((self.max_slots,), bool)
        for slot, req in active.items():
            token0[slot] = req.out_tokens[-1]
            pos0[slot] = req.next_pos
            write[slot] = True
        try:
            self.draft_cache, proposals = draft_propose(
                self.draft_cfg, self.draft_params, self.draft_cache,
                jnp.asarray(token0), jnp.asarray(pos0), k,
                jnp.asarray(write))
            proposals = np.asarray(proposals)  # [B, k]
            verify_tokens = np.concatenate(
                [token0[:, None], proposals], axis=1)  # [B, k+1]
            self.cache, logits = spec_verify_step(
                self.model_cfg, self.params, self.cache,
                jnp.asarray(verify_tokens), jnp.asarray(pos0),
                jnp.asarray(write))
            greedy = np.asarray(jnp.argmax(logits, axis=-1))  # [B, k+1]
        except Exception as e:  # noqa: BLE001 - caches donated & lost
            logger.exception("speculative step failed (%d active)",
                             len(active))
            self._recover_device_failure(f"speculative decode failed: {e!r}")
            return
        self.spec_ticks += 1
        for slot, req in active.items():
            accepted = 0
            while accepted < k and \
                    proposals[slot, accepted] == greedy[slot, accepted]:
                accepted += 1
            self.spec_proposed += k
            self.spec_accepted += accepted
            emit = [int(t) for t in proposals[slot, :accepted]]
            emit.append(int(greedy[slot, accepted]))  # corrected/bonus
            for tok in emit:
                if req.done.is_set():
                    break
                req.next_pos += 1
                self._emit(req, tok)
            # Draft KV is valid through the accepted prefix; draft_propose
            # writes k+1 entries, covering even the all-accepted case.
            req.draft_len = req.next_pos

    def _chunk_bucket(self, start: int, remaining: int) -> tuple[int, int]:
        """(bucket, take) for one prefill chunk starting at ``start``:
        power-of-two bucket from prefill_bucket_min, capped at
        prefill_chunk, and CLAMPED to the cache tail — a window crossing
        max_seq would make dynamic_update_slice clamp its start index and
        silently overwrite earlier positions."""
        bucket = self.config.prefill_bucket_min
        if self.blocked:
            # Chunks write whole pool blocks: buckets are power-of-two
            # multiples of block_size and starts stay block-aligned
            # (take == bucket on every non-final chunk).
            bucket = max(bucket, self.block_size)
        while bucket < min(remaining, self.config.prefill_chunk):
            bucket *= 2
        bucket = min(bucket, self.max_seq - start)
        return bucket, min(remaining, bucket)

    def _draft_catch_up(self, slot: int, req: GenerationRequest) -> bool:
        """Prefill the draft cache for positions draft_len..next_pos-1
        (the tokens already consumed by the target)."""
        seq = list(req.prompt_ids) + req.out_tokens[:-1]
        start = req.draft_len
        try:
            while start < req.next_pos:
                bucket, take = self._chunk_bucket(start,
                                                  req.next_pos - start)
                toks = np.zeros((bucket,), np.int32)
                toks[:take] = seq[start:start + take]
                self.draft_cache, _ = prefill_chunk(
                    self.draft_cfg, self.draft_params, self.draft_cache,
                    jnp.asarray(toks), jnp.int32(start),
                    jnp.int32(start + take), jnp.int32(slot))
                start += take
            req.draft_len = req.next_pos
            req.draft_fail_count = 0
            return True
        except Exception:  # noqa: BLE001 - draft trouble must not kill
            # the request; the caller falls back to plain decode. The
            # failed dispatch DONATED the draft cache — rebuild it, and
            # mark every speculating request's draft state cold. A request
            # that fails catch-up repeatedly (e.g. a span that OOMs the
            # draft prefill every tick) is speculation-disabled so it
            # stops zeroing everyone else's draft state each tick.
            logger.exception("draft catch-up failed for %s", req.request_id)
            req.draft_fail_count += 1
            if req.draft_fail_count >= 3:
                req.spec_disabled = True
                logger.warning("disabling speculation for %s after %d "
                               "failed draft catch-ups", req.request_id,
                               req.draft_fail_count)
            self.draft_cache = init_kv_cache(self.draft_cfg,
                                             self.max_slots, self.max_seq)
            for r in self._slots.values():
                if r is not None:
                    r.draft_len = 0
            return False

    def _sample_dispatch(self, logits, reqs):
        """Dispatch sampling on device; returns the (unfetched) token
        array so callers can defer the host roundtrip."""
        b = logits.shape[0]
        temps = np.zeros((b,), np.float32)
        top_ps = np.ones((b,), np.float32)
        top_k = 0
        for i, r in enumerate(reqs):
            if r is None:
                continue
            temps[i] = r.sampling.temperature
            top_ps[i] = r.sampling.top_p
            if r.sampling.top_k:
                top_k = max(top_k, r.sampling.top_k)
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sample_tokens(logits.astype(jnp.float32), jnp.asarray(temps),
                             jnp.asarray(top_ps), top_k, sub,
                             bool((top_ps < 1.0).any()))

    def _sample_one(self, logits, reqs) -> np.ndarray:
        return np.asarray(self._sample_dispatch(logits, reqs))

    def _emit(self, req: GenerationRequest, token: int) -> None:
        req.out_tokens.append(token)
        if len(req.out_tokens) == 1 and req.trace_ctx is not None:
            # First token: stamp the TTFT phase breakdown onto the
            # request's trace — queue wait (submit→admit) and the prefill
            # (or P/D KV import) interval ending at this emission.
            now = req.first_token_ts = time.time()
            if req.admit_ts and req.submit_ts:
                tracing.record_span(
                    "engine.queue", req.submit_ts, req.admit_ts,
                    ctx=req.trace_ctx,
                    attributes={"request_id": req.request_id})
            tracing.record_span(
                "engine.kv_import" if req.kv_imported
                else "engine.prefill",
                req.admit_ts or req.submit_ts or now, now,
                ctx=req.trace_ctx,
                attributes={"request_id": req.request_id,
                            "prompt_tokens": len(req.prompt_ids),
                            "prefix_adopted": req.prefilled_len})
        if req.stream_queue is not None:
            req.stream_queue.put(token)
        eos = {self.tokenizer.eos_id, *req.sampling.stop_token_ids}
        finish = None
        if token in eos:
            finish = "stop"
        elif len(req.out_tokens) >= req.sampling.max_tokens:
            finish = "length"
        elif req.next_pos + 1 >= self.max_seq:
            finish = "length"
        if finish:
            self._finish(req, finish)

    def _fail(self, req: GenerationRequest, err: str) -> None:
        """Fail one request: record the error, free its slot and any staged
        KV payload, and wake its waiter — the engine keeps serving others."""
        req.error = err
        req.preloaded = None
        req.hold_slot = False  # never pin a slot for a failed request
        self._finish(req, "error")

    def _finish(self, req: GenerationRequest, reason: str) -> None:
        req.finish_reason = reason
        if req.trace_ctx is not None and req.first_token_ts:
            tracing.record_span(
                "engine.decode", req.first_token_ts, time.time(),
                ctx=req.trace_ctx,
                attributes={"request_id": req.request_id,
                            "tokens": len(req.out_tokens),
                            "finish_reason": reason})
        for slot, r in self._slots.items():
            if r is req:
                req.last_slot = slot
                toks = self._prefix_live.pop(slot, None)
                if not req.hold_slot:
                    self._slots[slot] = None
                    if self.blocked:
                        # Pool mode: blocks go back to the pool instead of
                        # retiring as a cached prefix line.
                        self._free_slot_blocks(slot)
                        continue
                    if toks is not None and reason != "error":
                        # Retire, don't discard: the slot's KV stays intact
                        # until the slot is reclaimed, so an identical or
                        # shared-prefix prompt admits with zero prefill.
                        self._prefix_cached[slot] = (toks, time.monotonic())
        if req.stream_queue is not None:
            req.stream_queue.put(None)
        with self._submit_lock:
            self._requests.pop(req.request_id, None)
        req.done.set()

    def _result(self, req: GenerationRequest) -> GenerationResult:
        toks = req.out_tokens
        if toks and toks[-1] == self.tokenizer.eos_id:
            toks = toks[:-1]
        return GenerationResult(
            request_id=req.request_id, prompt_ids=req.prompt_ids,
            token_ids=list(toks), text=self.tokenizer.decode(toks),
            finish_reason=req.finish_reason or "stop")

    # ---- tensor parallel ----

    def _shard_for_tp(self, tp: int) -> None:
        """Shard params over a tp mesh axis; jit propagates shardings into
        prefill/decode (heads/kv_heads and mlp dims split over tp)."""
        from ray_tpu.models.llama import param_logical_axes
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh
        from ray_tpu.parallel.sharding import ShardingRules, shard_params

        devices = jax.devices()[:tp]
        if len(devices) < tp:
            raise ValueError(
                f"tensor_parallel_size={tp} but only {len(devices)} devices")
        self.mesh = build_mesh(MeshSpec(dp=1, fsdp=1, tp=tp), devices)
        self.params = shard_params(self.params, self.mesh,
                                   param_logical_axes(self.model_cfg),
                                   ShardingRules())


def _load_checkpoint(path: str):
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path)
