"""Job submission: run driver scripts on the cluster with status/log tracking.

Capability parity with the reference's job layer (reference:
python/ray/dashboard/modules/job/ — job_manager.py:62 JobManager spawns one
JobSupervisor actor per job (job_supervisor.py) which execs the entrypoint as
a subprocess with the job's runtime_env; status transitions
PENDING→RUNNING→{SUCCEEDED|FAILED|STOPPED} persisted in GCS KV; logs captured
per job): the supervisor actor here holds the child process, streams its
output into an in-actor buffer, and mirrors status into the cluster KV so any
client (HTTP or SDK) can query it.
"""

from __future__ import annotations

import json
import time
import uuid


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


_KV_NS = "jobs"


def _supervisor_class():
    """Defined lazily so the decorated class binds to the active runtime."""
    import ray_tpu

    # max_concurrency: run() blocks for the job's lifetime; stop()/logs()
    # must interleave (reference: the supervisor serves status RPCs while
    # the entrypoint runs).
    @ray_tpu.remote(num_cpus=0, max_concurrency=4)
    class JobSupervisor:
        """One per job; owns the entrypoint subprocess (reference:
        job_supervisor.py JobSupervisor actor)."""

        def __init__(self, submission_id: str, entrypoint: str,
                     env_vars: dict | None):
            self._id = submission_id
            self._entrypoint = entrypoint
            self._env_vars = env_vars or {}
            self._proc = None
            self._output: list[bytes] = []
            self._stopped = False

        def run(self) -> str:
            import os
            import subprocess
            import threading

            from ray_tpu.core.worker import global_worker

            rt = global_worker.runtime
            if self._stopped:  # stop_job arrived while the run task was queued
                _set_job_info(rt, self._id, status=JobStatus.STOPPED,
                              end_time=time.time())
                return JobStatus.STOPPED
            _set_job_info(rt, self._id, status=JobStatus.RUNNING,
                          start_time=time.time())
            try:
                env = dict(os.environ)
                env.update(self._env_vars)
                self._proc = subprocess.Popen(
                    self._entrypoint, shell=True, env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                )
                if self._stopped:
                    # stop() ran between the top-of-run check and Popen: it
                    # saw no child to signal, so terminate the child here or
                    # it runs to completion under a STOPPED record.
                    self._terminate_child()

                def pump():
                    for line in self._proc.stdout:
                        self._output.append(line)

                t = threading.Thread(target=pump, daemon=True)
                t.start()
                rc = self._proc.wait()
                t.join(timeout=5)
            except BaseException as e:  # noqa: BLE001
                # run.remote() is fire-and-forget: the error must land in the
                # job record, not in an unread object ref.
                _set_job_info(rt, self._id, status=JobStatus.FAILED,
                              end_time=time.time(), error=repr(e))
                raise
            if self._stopped:
                status = JobStatus.STOPPED
            elif rc == 0:
                status = JobStatus.SUCCEEDED
            else:
                status = JobStatus.FAILED
            _set_job_info(rt, self._id, status=status,
                          end_time=time.time(), returncode=rc)
            return status

        def stop(self) -> bool:
            if self._proc is None:
                # Not started yet: flag it so run() terminates immediately.
                self._stopped = True
                return True
            if self._proc.poll() is None:
                self._stopped = True
                self._terminate_child()
                return True
            return False  # already finished; don't rewrite history

        def _terminate_child(self, grace_s: float = 3.0) -> None:
            """SIGTERM, then SIGKILL after a grace period — an entrypoint
            that ignores SIGTERM must not stay RUNNING forever (reference:
            job_supervisor.py polls then escalates to SIGKILL)."""
            import threading

            proc = self._proc
            proc.terminate()

            def escalate():
                try:
                    proc.wait(timeout=grace_s)
                except Exception:
                    try:
                        proc.kill()
                    except Exception:
                        pass

            threading.Thread(target=escalate, daemon=True).start()

        def logs(self) -> str:
            return b"".join(self._output).decode(errors="replace")

        def ping(self) -> bool:
            return True

    return JobSupervisor


def _set_job_info(runtime, sid: str, **updates):
    key = sid
    raw = runtime.kv_get(key, ns=_KV_NS)
    info = json.loads(raw.decode()) if raw else {}
    info.update(updates)
    runtime.kv_put(key, json.dumps(info).encode(), ns=_KV_NS)


class JobManager:
    """Submission-side API; state in the cluster KV + one supervisor actor
    per job (reference: job_manager.py JobManager)."""

    def __init__(self):
        import ray_tpu

        ray_tpu.init(ignore_reinit_error=True)
        self._supervisors: dict[str, object] = {}

    def _runtime(self):
        from ray_tpu.core.worker import global_worker

        return global_worker.runtime

    # ---------------------------------------------------------------- submit
    def submit_job(self, *, entrypoint: str, submission_id: str | None = None,
                   runtime_env: dict | None = None,
                   metadata: dict | None = None) -> str:
        import ray_tpu

        submission_id = submission_id or f"job-{uuid.uuid4().hex[:12]}"
        if self._runtime().kv_get(submission_id, ns=_KV_NS) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        env_vars = dict((runtime_env or {}).get("env_vars") or {})
        _set_job_info(self._runtime(), submission_id,
                      submission_id=submission_id, entrypoint=entrypoint,
                      status=JobStatus.PENDING, metadata=metadata or {},
                      submit_time=time.time())
        supervisor_cls = _supervisor_class()
        options = {"name": f"_job_supervisor_{submission_id}"}
        if runtime_env:
            # working_dir/py_modules apply to the supervisor (and thus the
            # child's cwd); env_vars are passed to the child process directly.
            renv = {k: v for k, v in runtime_env.items() if k != "env_vars"}
            if renv:
                options["runtime_env"] = renv
        try:
            sup = supervisor_cls.options(**options).remote(
                submission_id, entrypoint, env_vars)
        except BaseException:
            # Never leave an unsupervised PENDING record behind.
            self._runtime().kv_del(submission_id, ns=_KV_NS)
            raise
        sup.run.remote()  # fire and forget; status lands in KV
        self._supervisors[submission_id] = sup
        return submission_id

    # ---------------------------------------------------------------- queries
    def get_job_info(self, submission_id: str) -> dict:
        raw = self._runtime().kv_get(submission_id, ns=_KV_NS)
        if raw is None:
            raise ValueError(f"no such job {submission_id!r}")
        return json.loads(raw.decode())

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def list_jobs(self) -> list[dict]:
        rt = self._runtime()
        out = []
        for key in rt.kv_keys(ns=_KV_NS):
            raw = rt.kv_get(key, ns=_KV_NS)
            if raw:
                out.append(json.loads(raw.decode()))
        return sorted(out, key=lambda j: j.get("submit_time", 0.0))

    def get_job_logs(self, submission_id: str) -> str:
        import ray_tpu

        sup = self._supervisor(submission_id)
        if sup is None:
            return ""
        return ray_tpu.get(sup.logs.remote())

    def stop_job(self, submission_id: str) -> bool:
        import ray_tpu

        self.get_job_info(submission_id)  # raises on unknown id
        sup = self._supervisor(submission_id)
        if sup is None:
            return False
        return ray_tpu.get(sup.stop.remote())

    def delete_job(self, submission_id: str) -> bool:
        import ray_tpu

        info = self.get_job_info(submission_id)
        if info["status"] not in JobStatus.TERMINAL:
            raise RuntimeError(
                f"job {submission_id!r} is {info['status']}; stop it first")
        sup = self._supervisor(submission_id)
        if sup is not None:
            # Free the actor (and its log buffer) and release the name so the
            # submission id can be reused.
            ray_tpu.kill(sup)
        self._runtime().kv_del(submission_id, ns=_KV_NS)
        self._supervisors.pop(submission_id, None)
        return True

    def _supervisor(self, submission_id: str):
        import ray_tpu

        sup = self._supervisors.get(submission_id)
        if sup is not None:
            return sup
        try:
            return ray_tpu.get_actor(f"_job_supervisor_{submission_id}")
        except ValueError:
            return None

    # ---------------------------------------------------------------- HTTP
    def attach_http(self, dashboard) -> None:
        """Register the job REST surface on a DashboardServer (reference:
        job REST API in dashboard/modules/job/job_head.py)."""

        def submit(params, body):
            req = json.loads(body.decode() or "{}")
            sid = self.submit_job(
                entrypoint=req["entrypoint"],
                submission_id=req.get("submission_id"),
                runtime_env=req.get("runtime_env"),
                metadata=req.get("metadata"),
            )
            return {"submission_id": sid}

        dashboard.add_route("POST", "/api/jobs/submit", submit)
        dashboard.add_route("GET", "/api/jobs/list",
                            lambda p, b: self.list_jobs())
        dashboard.add_route(
            "GET", "/api/jobs/status",
            lambda p, b: self.get_job_info(p["submission_id"]))
        dashboard.add_route(
            "GET", "/api/jobs/logs",
            lambda p, b: {"logs": self.get_job_logs(p["submission_id"])})
        dashboard.add_route(
            "POST", "/api/jobs/stop",
            lambda p, b: {"stopped": self.stop_job(
                json.loads(b.decode())["submission_id"])})
        dashboard.add_route(
            "POST", "/api/jobs/delete",
            lambda p, b: {"deleted": self.delete_job(
                json.loads(b.decode())["submission_id"])})
