from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalingConfig, NodeTypeConfig
from ray_tpu.autoscaler.instance_manager import Instance, InstanceManager, InstanceStatus
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider,
    NodeProvider,
    TpuSliceProvider,
)
from ray_tpu.autoscaler.gcp import (
    GceNodeProvider,
    GcpTpuQueuedResourceClient,
    tpu_slice_provider_from_gcp,
)
from ray_tpu.autoscaler.scheduler import bin_pack_demands

__all__ = [
    "Autoscaler",
    "AutoscalingConfig",
    "NodeTypeConfig",
    "Instance",
    "InstanceManager",
    "InstanceStatus",
    "NodeProvider",
    "FakeMultiNodeProvider",
    "GceNodeProvider",
    "GcpTpuQueuedResourceClient",
    "tpu_slice_provider_from_gcp",
    "TpuSliceProvider",
    "bin_pack_demands",
]
