"""DirectChannel: peer-to-peer compiled-graph dataflow off the head.

The head-KV channel (`channel.StoreChannel`) pays two control-plane RPCs
per hop per step and busy-polls the head for arrival. This transport moves
every payload peer-to-peer over the same push-frame path direct actor calls
ride (reference: the experimental_mutable_object_manager transport behind
python/ray/experimental/channel/ — writers push into the reader's local
store, readers block locally):

- **Route exchange once, at compile time.** Each reader publishes
  ``dagchan/<name>/<idx>`` → (worker, host, port, node) to the head KV when
  it first attaches; the writer resolves each route once and caches it for
  the channel's lifetime. After warmup the steady state issues ZERO head
  RPCs per step.
- **Data plane.** Small payloads ride inline in a ``dag_chan_push`` frame
  to the reader's own RPC server (every cluster process runs one). Large
  payloads — activations/grads — are placed in the object plane as
  store-backed buffers (node shm arena beyond the threshold) and the frame
  carries only the ref: same-host readers map a pinned arena view
  (zero-copy), cross-host readers pull ranges over the native transfer
  plane. The ndarray fast path of ``serialization.serialize_parts`` means
  array payloads are never pickled byte-by-byte on the hot path.
- **Backpressure.** The reader acks a frame only after its ``read()``
  dequeued AND materialized the value; the writer keeps at most
  ``capacity`` writes unacked and blocks on the oldest beyond that. A dead
  reader process fails the pending acks (``RpcConnectionLost``), which
  surfaces as ``ChannelClosed`` at the writer instead of a silent wedge.

Known limitation (shared with StoreChannel): in a fan-in schedule where one
input closes while a peer writer is ack-blocked mid-write, that writer
unwedges only when its reader process exits or the DAG is destroyed
(``destroy()`` force-closes every registered reader peer-to-peer).
"""

from __future__ import annotations

import asyncio
import json
import queue
import threading
import time
from collections import deque
from typing import Any

from ray_tpu.dag.channel import ChannelClosed
from ray_tpu.util import tracing
from ray_tpu.utils import serialization
from ray_tpu.utils.config import get_config

_ROUTE_NS = "channels"


class _Receiver:
    """Per-(channel, reader-index) inbound frame queue of this process.

    Unbounded on purpose: the io loop's enqueue must never block (writer
    windows — not queue depth — bound memory: at most ``capacity`` unacked
    frames per writer exist at once)."""

    __slots__ = ("queue",)

    def __init__(self):
        self.queue: queue.Queue = queue.Queue()


_receivers: dict[tuple[str, int], _Receiver] = {}
_recv_lock = threading.Lock()


def _receiver(name: str, idx: int) -> _Receiver:
    with _recv_lock:
        r = _receivers.get((name, idx))
        if r is None:
            r = _receivers[(name, idx)] = _Receiver()
        return r


def _drop_receivers(name: str) -> None:
    with _recv_lock:
        for key in [k for k in _receivers if k[0] == name]:
            _receivers.pop(key, None)


def handle_chan_push(conn, msg: dict) -> None:
    """Raw RPC handler (io-loop inline, registered on every cluster
    process's server): enqueue the frame for the local reader thread. The
    reply is NOT sent here — the reader acks from ``read()`` after
    materializing, which is what makes writer-side capacity into real
    end-to-end backpressure."""
    a = msg.get("a") or {}
    rid = msg.get("i")
    ack = None
    if rid is not None:
        loop = asyncio.get_running_loop()
        from ray_tpu.core.cluster.protocol import pack_reply

        def ack(err: str | None = None, *, _rid=rid, _conn=conn, _loop=loop):
            frame = pack_reply(_rid, True) if err is None else \
                pack_reply(_rid, err=err)
            _loop.call_soon_threadsafe(_conn.post, frame)

    _receiver(a["chan"], a.get("ridx", 0)).queue.put((a, ack))


class DirectChannel:
    """Single-writer multi-reader channel over direct push frames.

    Pickles by identity (name + shape); cursors, routes, and the runtime
    binding are per-process, exactly like StoreChannel."""

    def __init__(self, name: str, num_readers: int = 1,
                 capacity: int | None = None,
                 inline_max: int | None = None):
        cfg = get_config()
        self.name = name
        self.num_readers = num_readers
        self.capacity = capacity if capacity is not None \
            else cfg.dag_channel_capacity
        self.inline_max = inline_max if inline_max is not None \
            else cfg.dag_inline_max_bytes
        self._init_state()

    def _init_state(self):
        self._runtime = None
        self._routes: dict[int, tuple] = {}  # ridx -> (worker, host, port)
        self._outstanding: deque = deque()  # (ack cf-futures, held ref)
        self._write_seq = 0
        self._registered: set[int] = set()
        self._closed_local = False

    def __getstate__(self):
        return {"name": self.name, "num_readers": self.num_readers,
                "capacity": self.capacity, "inline_max": self.inline_max}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._init_state()

    def connect(self, runtime) -> "DirectChannel":
        if self._runtime is None:
            self._runtime = runtime
        return self

    # ---------------------------------------------------------------- routes
    def _route_key(self, reader_index: int) -> str:
        return f"dagchan/{self.name}/{reader_index}"

    def ensure_reader(self, reader_index: int = 0) -> None:
        """Attach this process as the channel's ``reader_index`` reader:
        create the local frame queue FIRST, then publish the route (the one
        compile-time head write) — any frame that finds the route finds the
        queue."""
        if reader_index in self._registered:
            return
        assert self._runtime is not None, "channel not connected"
        rt = self._runtime
        _receiver(self.name, reader_index)
        route = [rt.worker_id.hex(), rt.addr[0], rt.addr[1],
                 getattr(rt, "my_node_id", "") or ""]
        rt.kv_put(self._route_key(reader_index),
                  json.dumps(route).encode(), ns=_ROUTE_NS)
        self._registered.add(reader_index)

    def _resolve_route(self, reader_index: int,
                       timeout: float | None = 60.0) -> tuple | None:
        """Writer-side route lookup, cached for the channel's lifetime.
        Polls the KV until the reader has attached (compile/warmup time
        only — never on the per-step path)."""
        route = self._routes.get(reader_index)
        if route is not None:
            return route
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            raw = self._runtime.kv_get(self._route_key(reader_index),
                                       ns=_ROUTE_NS)
            if raw is not None:
                route = tuple(json.loads(bytes(raw)))
                if route[0] != self._runtime.worker_id.hex():
                    # Warm the peer connection NOW: with the client cached,
                    # every later send's coroutine runs to its frame write
                    # without suspending, so wire order == write() order
                    # (racing first-sends could otherwise land on two
                    # different connections and reorder). The ROUTE timeout
                    # does not govern this step: timeout=0 means "don't
                    # wait for a reader that never attached", but once the
                    # route exists the connect must get a real budget (a
                    # zero-budget connect would silently drop force-close
                    # frames at destroy time).
                    self._runtime._io.run(
                        self._runtime._apeer((route[1], route[2])),
                        timeout=None if timeout is None
                        else max(timeout, 5.0))
                self._routes[reader_index] = route
                return route
            if deadline is not None and time.monotonic() > deadline:
                return None
            time.sleep(0.005)

    # ---------------------------------------------------------------- write
    def _send(self, route: tuple, payload: dict):
        """Ship one frame to a reader; returns a concurrent future that
        resolves when the reader ACKS (has read + materialized the value).
        Same-process readers bypass the wire entirely."""
        rt = self._runtime
        import concurrent.futures as cf

        if route[0] == rt.worker_id.hex():
            fut: cf.Future = cf.Future()

            def ack(err: str | None = None):
                if err is None:
                    fut.set_result(True)
                else:
                    fut.set_exception(ChannelClosed(
                        f"{self.name}: reader failed: {err}"))

            _receiver(self.name, payload.get("ridx", 0)).queue.put(
                (payload, ack))
            return fut

        addr = (route[1], route[2])

        async def go():
            cli = await rt._apeer(addr)
            return await cli.call_nowait("dag_chan_push", **payload)

        return asyncio.run_coroutine_threadsafe(go(), rt._io.loop)

    def write(self, value: Any) -> None:
        assert self._runtime is not None, "channel not connected"
        if self._closed_local:
            raise ChannelClosed(self.name)
        rt = self._runtime
        parts = serialization.serialize_parts(value)
        total = sum(len(p) for p in parts)
        payload: dict = {"chan": self.name, "seq": self._write_seq}
        # Trace context rides INSIDE the existing push frame (no new RPC,
        # no extra frame): the reader's hop span parents under this
        # write's span, so a DAG step is one trace across processes.
        tspan = None
        if tracing.current_context() is not None:
            tspan = tracing.start_span(
                f"dag.push.{self.name}", kind="client",
                attributes={"seq": self._write_seq, "bytes": total,
                            "inline": total <= self.inline_max})
            payload["trace"] = tracing.ctx_for(tspan,
                                               tracing.current_sampled())
        ref = None
        if total <= self.inline_max:
            payload["data"] = b"".join(bytes(p) for p in parts)
        else:
            # Store-backed buffer: bytes land once in the object plane
            # (node arena when large); the frame carries the ref plus our
            # own address so the reader never resolves us through the head.
            from ray_tpu.core.object_ref import ObjectRef
            from ray_tpu.utils.ids import ObjectID

            oid = ObjectID.for_put(rt.worker_id)
            rt._store_blob(oid, parts, rt.worker_id)
            rt.refs.add_owned(oid, rt.worker_id, local_refs=1)
            ref = ObjectRef.counted(oid, rt.worker_id)
            payload.update(oid=oid.hex(), owner=rt.worker_id.hex(),
                           whost=rt.addr[0], wport=rt.addr[1],
                           wnode=getattr(rt, "my_node_id", "") or "")
        futs = []
        try:
            for ridx in range(self.num_readers):
                route = self._resolve_route(ridx)
                if route is None:
                    raise TimeoutError(
                        f"channel {self.name}: reader {ridx} never attached")
                futs.append(self._send(route, dict(payload, ridx=ridx)))
        finally:
            if tspan is not None:
                tracing.finish_span(tspan, tracing.current_sampled())
        # The held ref keeps the store-backed buffer alive until every
        # reader acked; dropped when the entry drains off the window.
        self._outstanding.append((futs, ref))
        self._write_seq += 1
        while len(self._outstanding) > self.capacity:
            self._drain_oldest()

    def _drain_oldest(self) -> None:
        import concurrent.futures as cf

        futs, _ref = self._outstanding.popleft()
        for f in futs:
            while True:
                try:
                    f.result(timeout=0.5)
                    break
                except (cf.TimeoutError, TimeoutError):
                    continue  # backpressure stall: reader still busy
                except ChannelClosed:
                    raise
                except Exception as e:  # conn lost / reader errored
                    raise ChannelClosed(
                        f"{self.name}: reader gone: {e!r}") from e

    def flush(self, timeout: float | None = None) -> None:
        """Block until every outstanding write is acked (bench/test hook)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._outstanding:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} flush")
            self._drain_oldest()

    # ---------------------------------------------------------------- read
    def read(self, reader_index: int = 0,
             timeout: float | None = None) -> Any:
        assert self._runtime is not None, "channel not connected"
        self.ensure_reader(reader_index)
        q = _receiver(self.name, reader_index).queue
        try:
            a, ack = q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"channel {self.name}") from None
        if a.get("close"):
            # Re-enqueue so every subsequent read re-raises immediately.
            q.put((a, None))
            if ack is not None:
                ack()
            raise ChannelClosed(self.name)
        t_deq = time.time()
        try:
            value = self._materialize(a)
        except BaseException as e:
            if ack is not None:
                ack(err=repr(e))
            raise
        if ack is not None:
            ack()
        tctx = a.get("trace")
        if tctx is not None:
            # Reader hop span: dequeue → materialized, parented under the
            # writer's push span via the context the frame carried. The
            # reading thread then ADOPTS the context: a DAG actor loop's
            # downstream write re-injects it, chaining the next hop onto
            # the same trace across any number of stages.
            s = tracing.record_span(
                f"dag.recv.{self.name}", t_deq, time.time(), kind="worker",
                attributes={"seq": a.get("seq", -1),
                            "inline": "data" in a,
                            "reader_index": reader_index},
                ctx=tctx)
            tracing.adopt(tracing.ctx_for(s, tctx.get("sampled"))
                          if s is not None else tctx)
        else:
            tracing.adopt(None)  # untraced frame: don't inherit the last
        return value

    def _materialize(self, a: dict) -> Any:
        data = a.get("data")
        if data is not None:
            return serialization.deserialize(data)
        rt = self._runtime
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.utils.ids import ObjectID, WorkerID

        oid = ObjectID.from_hex(a["oid"])
        # Same-host fast path: pinned arena view, zero copies, zero RPCs.
        blob = rt._local_blob(oid, as_view=True)
        if blob is not None:
            return serialization.deserialize(blob)
        # Cross-host: seed the worker directory from the frame's route info
        # so the borrower pull targets the writer directly (transfer-plane
        # range pulls) without a head resolve.
        owner_hex = a["owner"]
        if a.get("whost"):
            rt._worker_dir_cache[owner_hex] = (
                time.monotonic(), (a["whost"], a["wport"]),
                a.get("wnode", ""))
        ref = ObjectRef(oid, WorkerID.from_hex(owner_hex))
        return rt.get([ref])[0]

    # ---------------------------------------------------------------- close
    def _send_close(self, reader_index: int, route_timeout: float) -> None:
        """Unacked close marker (notify frame): a reader whose loop already
        exited would never ack, and teardown must not wait on it."""
        try:
            route = self._resolve_route(reader_index, timeout=route_timeout)
        except Exception:
            route = None
        if route is None:
            return
        payload = {"chan": self.name, "ridx": reader_index, "close": True}
        rt = self._runtime
        if route[0] == rt.worker_id.hex():
            _receiver(self.name, reader_index).queue.put((payload, None))
            return
        addr = (route[1], route[2])

        async def go():
            cli = await rt._apeer(addr)
            await cli.notify("dag_chan_push", **payload)

        try:
            asyncio.run_coroutine_threadsafe(
                go(), rt._io.loop).result(timeout=5.0)
        except Exception:
            pass  # peer gone: its loops are dead anyway

    def close(self) -> None:
        """Writer-side close: FIFO close marker to every attached reader
        (queued behind any unread data frames, exactly like the KV
        channel's append-only marker)."""
        if self._closed_local:
            return
        self._closed_local = True
        for ridx in range(self.num_readers):
            self._send_close(ridx, route_timeout=2.0)

    def destroy(self) -> None:
        """Teardown: force-close every reader that ever attached (unblocks
        loops wedged on a dead upstream), then reclaim the route keys and
        this process's receiver queues."""
        rt = self._runtime
        if rt is None:
            return
        self._closed_local = True
        for ridx in range(self.num_readers):
            self._send_close(ridx, route_timeout=0.0)
        self._outstanding.clear()
        for key in rt.kv_keys(prefix=f"dagchan/{self.name}/", ns=_ROUTE_NS):
            rt.kv_del(key, ns=_ROUTE_NS)
        _drop_receivers(self.name)


__all__ = ["DirectChannel", "handle_chan_push"]
