"""Benchmark: Llama causal-LM training-step throughput, tokens/sec/chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

vs_baseline is FLOP-normalized against the reference north-star (BASELINE.md:
Llama-3-8B DDP fine-tune at ~3,300 tokens/sec per A100-class chip, i.e.
6·N·rate ≈ 1.59e14 training FLOP/s/chip): vs_baseline = (6·N·tokens_per_sec)
/ 1.59e14 — >1.0 means this chip trains more model-FLOPs per second than the
reference's A100 number.
"""

from __future__ import annotations

import json
import sys
import time


A100_8B_TOKENS_PER_SEC = 3300.0
A100_8B_PARAMS = 8.03e9
BASELINE_FLOPS = 6.0 * A100_8B_PARAMS * A100_8B_TOKENS_PER_SEC  # 1.59e14


def _tpu_reachable(timeout: float = 90.0) -> bool:
    """Probe the TPU backend in a subprocess — backend init can hang
    indefinitely if the device tunnel is down, and it must not take the
    bench process with it."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert any(d.platform == 'tpu' for d in jax.devices())"],
            timeout=timeout, capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False


def main() -> None:
    on_tpu = _tpu_reachable()
    import jax

    if not on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.spmd import make_llama_train_step

    if on_tpu:
        # ~1.1B-param geometry (Llama-3.2-1B-like), bf16, remat.
        cfg = LlamaConfig(
            vocab_size=32128, hidden_size=2048, intermediate_size=8192,
            num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
            max_seq_len=2048, tie_embeddings=True, dtype="bfloat16",
        )
        seq = 2048
        # (batch, remat, attn) in preference order: no remat avoids the 33%
        # recompute tax when activations fit; 'dots' saves matmul outputs
        # only; full remat is the memory floor.
        candidates = [
            (4, "dots+", "flash"), (8, "dots+", "flash"),
            (4, "dots", "flash"), (4, "full", "flash"),
            (8, "full", "flash"), (2, "full", "flash"),
            (4, "full", "blockwise"),
        ]
        steps, warmup = 10, 2
        metric = "llama_1b_train_tokens_per_sec_per_chip"
    else:
        cfg = LlamaConfig.tiny()
        seq = 128
        candidates = [(4, "full", "blockwise")]
        steps, warmup = 3, 1
        metric = "llama_tiny_train_tokens_per_sec_cpu_fallback"

    n_params = cfg.num_params()
    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])

    last_err = None
    state = step_fn = None
    for batch, remat, attn in candidates:
            try:
                opt = optax.adamw(3e-4, weight_decay=0.1,
                                  mu_dtype=jnp.bfloat16)
                step_fn, init_state, shard = make_llama_train_step(
                    cfg, mesh, optimizer=opt, attn_impl=attn, remat=remat,
                )
                state = init_state()
                rng = np.random.default_rng(0)
                tokens = shard(rng.integers(0, cfg.vocab_size, (batch, seq),
                                            dtype=np.int32))
                targets = shard(np.roll(np.asarray(tokens), -1, axis=1))
                for _ in range(warmup):
                    state, m = step_fn(state, tokens, targets)
                jax.block_until_ready(m["loss"])
                t0 = time.perf_counter()
                for _ in range(steps):
                    state, m = step_fn(state, tokens, targets)
                jax.block_until_ready(m["loss"])
                dt = (time.perf_counter() - t0) / steps
                tok_per_sec = batch * seq / dt
                vs = (6.0 * n_params * tok_per_sec) / BASELINE_FLOPS
                print(json.dumps({
                    "metric": metric,
                    "value": round(tok_per_sec, 1),
                    "unit": "tokens/sec/chip",
                    "vs_baseline": round(vs, 3),
                }))
                return
            except Exception as e:  # noqa: BLE001 - OOM/compile fallback chain
                last_err = e
                print(f"candidate {(batch, remat, attn)} failed: "
                      f"{str(e)[:200]}", file=sys.stderr)
                # Drop every live buffer from the failed candidate before the
                # next one allocates — otherwise a single OOM leaks ~9 GB of
                # params/optimizer state and cascades down the whole chain.
                state = step_fn = None
                for buf in jax.live_arrays():
                    buf.delete()
                jax.clear_caches()
                continue
    print(json.dumps({
        "metric": metric, "value": 0.0, "unit": "tokens/sec/chip",
        "vs_baseline": 0.0,
    }))
    print(f"bench failed: {last_err}", file=sys.stderr)


if __name__ == "__main__":
    main()
