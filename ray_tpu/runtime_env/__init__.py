from ray_tpu.runtime_env.manager import RuntimeEnvManager, get_manager
from ray_tpu.runtime_env.plugin import RuntimeEnvPlugin, register_plugin
from ray_tpu.runtime_env.runtime_env import RuntimeEnv

__all__ = [
    "RuntimeEnv",
    "RuntimeEnvManager",
    "RuntimeEnvPlugin",
    "get_manager",
    "register_plugin",
]
