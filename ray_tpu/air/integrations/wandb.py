"""Weights & Biases integration (reference: python/ray/air/integrations/
wandb.py WandbLoggerCallback/setup_wandb). wandb is not part of this image;
the callback degrades to an informative error at construction so a run
config referencing it fails fast rather than mid-run.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.air.integrations.base import Callback


def _import_wandb():
    try:
        import wandb  # noqa: F401
        return wandb
    except ImportError as e:
        raise ImportError(
            "wandb is not installed in this environment; use "
            "JsonLoggerCallback/CSVLoggerCallback/TBXLoggerCallback, or "
            "install wandb where permitted.") from e


class WandbLoggerCallback(Callback):
    def __init__(self, project: str, name: str | None = None, **init_kw):
        self._wandb = _import_wandb()
        self.project, self.name, self.init_kw = project, name, init_kw
        self._run = None

    def on_run_start(self, run_name: str, config: dict | None) -> None:
        self._run = self._wandb.init(
            project=self.project, name=self.name or run_name,
            config=config, **self.init_kw)

    def on_result(self, metrics: dict, iteration: int) -> None:
        if self._run is not None:
            self._run.log(metrics, step=iteration)

    def on_run_end(self, result: Any) -> None:
        if self._run is not None:
            self._run.finish()


def setup_wandb(config: dict | None = None, **kw):
    """Per-worker setup inside a train loop (reference: setup_wandb)."""
    return _import_wandb().init(config=config, **kw)
