"""Native shm object store tests (reference test model: plasma client tests
src/ray/object_manager/plasma/ + test_plasma* in python/ray/tests/)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from ray_tpu.core.shm_store import SharedMemoryStore, ShmStoreError


@pytest.fixture
def store():
    name = f"rtpu_test_{os.getpid()}"
    s = SharedMemoryStore(name, capacity_bytes=1 << 20, create=True)
    yield s
    s.destroy()


def test_put_get_roundtrip(store):
    store.put(b"a" * 20, b"hello world")
    assert store.get_bytes(b"a" * 20) == b"hello world"
    assert store.contains(b"a" * 20)
    assert not store.contains(b"b" * 20)


def test_zero_copy_view_and_release(store):
    arr = np.arange(1000, dtype=np.float32)
    store.put(b"c" * 20, arr.tobytes())
    view = store.get(b"c" * 20)
    out = np.frombuffer(view, dtype=np.float32)
    np.testing.assert_array_equal(out, arr)
    # Pinned objects refuse deletion until released.
    with pytest.raises(ShmStoreError):
        store.delete(b"c" * 20)
    del out
    view.release()
    store.release(b"c" * 20)
    store.delete(b"c" * 20)
    assert not store.contains(b"c" * 20)


def test_idempotent_put_and_arbitrary_ids(store):
    store.put(b"some-long-object-id-string", b"v1")
    store.put(b"some-long-object-id-string", b"v2")  # no-op
    assert store.get_bytes(b"some-long-object-id-string") == b"v1"


def test_many_objects_alloc_free_reuse(store):
    # Fill/free cycles must reuse arena space (coalescing works).
    for cycle in range(5):
        ids = []
        for i in range(50):
            oid = f"obj-{cycle}-{i}".encode()
            store.put(oid, bytes([i % 256]) * 10_000)
            ids.append(oid)
        for oid in ids:
            store.delete(oid)
    assert store.stats()["num_objects"] == 0


def test_spill_on_oom_and_restore(store):
    # Capacity 1 MiB; write 8 × 200 KiB → earlier objects spill to disk.
    blobs = {f"blob{i}".encode(): os.urandom(200_000) for i in range(8)}
    for oid, data in blobs.items():
        store.put(oid, data)
    st = store.stats()
    assert st["num_spilled"] > 0
    # Every object is still readable (restored transparently).
    for oid, data in blobs.items():
        assert store.get_bytes(oid) == data


def test_oversized_object_rejected(store):
    with pytest.raises(ShmStoreError):
        store.put(b"huge", os.urandom(2 << 20))


def test_cross_process_attach():
    """A second process attaches to the same segment and reads/writes."""
    name = f"rtpu_xproc_{os.getpid()}"
    s = SharedMemoryStore(name, capacity_bytes=1 << 20, create=True)
    try:
        s.put(b"shared-key", b"from-parent")
        child = textwrap.dedent(f"""
            import sys
            from ray_tpu.core.shm_store import SharedMemoryStore
            s = SharedMemoryStore({name!r}, create=False)
            assert s.get_bytes(b"shared-key") == b"from-parent"
            s.put(b"child-key", b"from-child")
            s.close()
            print("child-ok")
        """)
        out = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))},
            timeout=60)
        assert out.returncode == 0, out.stderr
        assert "child-ok" in out.stdout
        assert s.get_bytes(b"child-key") == b"from-child"
    finally:
        s.destroy()


def test_concurrent_multiprocess_writers():
    """N writer processes hammer the same store; all objects land intact
    (exercises the robust process-shared mutex)."""
    name = f"rtpu_mp_{os.getpid()}"
    s = SharedMemoryStore(name, capacity_bytes=1 << 22, create=True)
    try:
        workers = []
        for w in range(3):
            code = textwrap.dedent(f"""
                from ray_tpu.core.shm_store import SharedMemoryStore
                s = SharedMemoryStore({name!r}, create=False)
                for i in range(30):
                    s.put(f"w{w}-{{i}}".encode(), (str({w}) * 100 + str(i)).encode())
                s.close()
            """)
            workers.append(subprocess.Popen(
                [sys.executable, "-c", code],
                env={**os.environ, "PYTHONPATH": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))}))
        for p in workers:
            assert p.wait(timeout=120) == 0
        for w in range(3):
            for i in range(30):
                data = s.get_bytes(f"w{w}-{i}".encode())
                assert data == (str(w) * 100 + str(i)).encode()
    finally:
        s.destroy()
