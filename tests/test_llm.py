"""LLM engine + serving tests (reference test model: vLLM-engine stage tests
in ray.llm tests; here the engine itself is under test)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams
from ray_tpu.llm.engine import decode_step, init_kv_cache, prefill, sample_tokens
from ray_tpu.models.llama import LlamaConfig, forward, init_params


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_prefill_decode_matches_full_forward(tiny):
    """Incremental decoding must produce the same logits as a full forward
    pass over the concatenated sequence (the KV-cache correctness spec)."""
    cfg, params = tiny
    prompt = np.array([5, 7, 11, 13], np.int32)
    n_extra = 3
    cache = init_kv_cache(cfg, max_slots=2, max_seq=32)

    # Reference: full forward over prompt + extra tokens.
    extra = np.array([17, 19, 23], np.int32)
    full = np.concatenate([prompt, extra])
    ref_logits = np.asarray(
        forward(cfg, params, jnp.asarray(full)[None], attn_impl="blockwise",
                remat=False))[0]

    # Engine path: prefill the prompt, then decode the extra tokens one by
    # one in slot 1 (slot 0 stays empty to catch slot-indexing bugs).
    toks = np.zeros((16,), np.int32)
    toks[:4] = prompt
    cache, last = prefill(cfg, params, cache, jnp.asarray(toks),
                          jnp.int32(4), jnp.int32(1))
    np.testing.assert_allclose(np.asarray(last), ref_logits[3], rtol=2e-4,
                               atol=2e-4)

    for i in range(n_extra):
        tokens = np.zeros((2,), np.int32)
        positions = np.zeros((2,), np.int32)
        tokens[1] = extra[i]
        positions[1] = 4 + i
        cache, logits = decode_step(cfg, params, cache,
                                    jnp.asarray(tokens),
                                    jnp.asarray(positions))
        np.testing.assert_allclose(np.asarray(logits[1]), ref_logits[4 + i],
                                   rtol=2e-4, atol=2e-4)


def test_sample_tokens_greedy_and_topp():
    logits = jnp.asarray([[0.0, 5.0, 1.0, 2.0],
                          [10.0, 0.0, 0.0, 0.0]], jnp.float32)
    # Greedy (temp 0)
    out = sample_tokens(logits, jnp.zeros(2), jnp.ones(2), 0,
                        jax.random.PRNGKey(0))
    assert list(np.asarray(out)) == [1, 0]
    # top_p=tiny keeps only the argmax even at high temperature
    out = sample_tokens(logits, jnp.full((2,), 5.0), jnp.full((2,), 1e-6), 0,
                        jax.random.PRNGKey(1))
    assert list(np.asarray(out)) == [1, 0]
    # top_k=1 likewise
    out = sample_tokens(logits, jnp.full((2,), 5.0), jnp.ones(2), 1,
                        jax.random.PRNGKey(2))
    assert list(np.asarray(out)) == [1, 0]


def test_engine_generate_deterministic():
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        r1 = eng.generate("hello", SamplingParams(max_tokens=8))
        r2 = eng.generate("hello", SamplingParams(max_tokens=8))
        assert r1.token_ids == r2.token_ids  # greedy → deterministic
        assert 0 < len(r1.token_ids) <= 8
        assert r1.finish_reason in ("stop", "length")
    finally:
        eng.shutdown()


def test_engine_continuous_batching_concurrent():
    """More concurrent requests than slots: all must complete, and the
    engine must have had >1 slot active at once (continuous batching)."""
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        peak = [0]
        stop = threading.Event()

        def watch():
            while not stop.is_set():
                peak[0] = max(peak[0], eng.stats()["active"])

        w = threading.Thread(target=watch, daemon=True)
        w.start()
        results = [None] * 5
        def gen(i):
            results[i] = eng.generate(f"prompt number {i}",
                                      SamplingParams(max_tokens=12))
        threads = [threading.Thread(target=gen, args=(i,)) for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        assert all(r is not None for r in results)
        assert peak[0] >= 2
        # Each result matches its own solo regeneration (no cross-request
        # cache contamination).
        solo = eng.generate("prompt number 3", SamplingParams(max_tokens=12))
        assert solo.token_ids == results[3].token_ids
    finally:
        eng.shutdown()


def test_engine_streaming():
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        chunks = list(eng.generate_stream("stream me",
                                          SamplingParams(max_tokens=6)))
        assert 1 <= len(chunks) <= 6
    finally:
        eng.shutdown()


def test_llm_server_openai_surface():
    ray_tpu.init()
    try:
        from ray_tpu import serve
        from ray_tpu.llm import build_openai_app

        app = build_openai_app(LLMConfig(model="tiny", max_num_seqs=2,
                                         max_seq_len=64))
        handle = serve.run(app, route_prefix=None, _blocking_timeout=120.0)
        out = handle.completions.remote("hi there").result(timeout=120)
        assert out["object"] == "text_completion"
        assert isinstance(out["choices"][0]["text"], str)
        assert out["usage"]["completion_tokens"] > 0

        chat = handle.chat.remote(
            [{"role": "user", "content": "hello"}]).result(timeout=120)
        assert chat["choices"][0]["message"]["role"] == "assistant"
        serve.shutdown()
    finally:
        ray_tpu.shutdown()


def test_chunked_prefill_matches_full(tiny):
    """prefill_chunk over N chunks must equal one whole-prompt prefill
    (same cache contents, same last-token logits)."""
    from ray_tpu.llm.engine import prefill_chunk

    cfg, params = tiny
    prompt = np.arange(1, 13, dtype=np.int32)  # 12 tokens
    p = len(prompt)

    cache_full = init_kv_cache(cfg, max_slots=2, max_seq=32)
    toks = np.zeros((16,), np.int32)
    toks[:p] = prompt
    cache_full, last_full = prefill(cfg, params, cache_full,
                                    jnp.asarray(toks), jnp.int32(p),
                                    jnp.int32(1))

    cache_c = init_kv_cache(cfg, max_slots=2, max_seq=32)
    last_c = None
    for start in range(0, p, 4):  # 3 chunks of 4
        chunk = np.zeros((4,), np.int32)
        chunk[:] = prompt[start:start + 4]
        cache_c, last_c = prefill_chunk(cfg, params, cache_c,
                                        jnp.asarray(chunk),
                                        jnp.int32(start), jnp.int32(p),
                                        jnp.int32(1))
    np.testing.assert_allclose(np.asarray(last_c), np.asarray(last_full),
                               rtol=2e-4, atol=2e-4)
    # cache contents match where real tokens live
    np.testing.assert_allclose(
        np.asarray(cache_c["k"][:, 1, :, :p]).astype(np.float32),
        np.asarray(cache_full["k"][:, 1, :, :p]).astype(np.float32),
        rtol=2e-3, atol=2e-3)


def test_decode_write_mask_protects_prefilling_slot(tiny):
    """A slot mid-prefill must not be corrupted by the batched decode's
    writes (write_mask=False keeps the cache line)."""
    cfg, params = tiny
    cache = init_kv_cache(cfg, max_slots=2, max_seq=32)
    before = np.asarray(cache["k"][:, 0]).copy()
    tokens = np.array([99, 3], np.int32)
    positions = np.array([0, 0], np.int32)
    write = np.array([False, True])
    cache, _ = decode_step(cfg, params, cache, jnp.asarray(tokens),
                           jnp.asarray(positions), jnp.asarray(write))
    after = np.asarray(cache["k"][:, 0])
    np.testing.assert_array_equal(before, after)  # slot 0 untouched
    assert np.abs(np.asarray(cache["k"][:, 1, :, 0])).sum() > 0  # slot 1 written


def test_engine_long_prompt_chunked():
    """A prompt longer than prefill_chunk completes across chunks."""
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=96)
    cfg.prefill_chunk = 16
    eng = LLMEngine(cfg)
    try:
        prompt = list(np.random.default_rng(0).integers(1, 200, 40))
        out = eng.generate(prompt, SamplingParams(max_tokens=4,
                                                  temperature=0.0),
                           timeout=120)
        assert len(out.token_ids) >= 1
    finally:
        eng.shutdown()


def test_openai_sse_streaming():
    """stream: true returns chat.completion.chunk SSE frames ending with
    [DONE] (reference: OpenAI-compatible streaming ingress)."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.serving import build_openai_app

    ray_tpu.init()
    try:
        cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128)
        serve.run(build_openai_app(cfg), route_prefix="/", http=True)
        port = serve.http_port()
        body = _json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 5, "temperature": 0.0, "stream": True,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")
            text = r.read().decode()
        frames = [ln[6:] for ln in text.splitlines()
                  if ln.startswith("data: ") and ln != "data: [DONE]"]
        assert text.rstrip().endswith("data: [DONE]")
        parsed = [_json.loads(f) for f in frames]
        assert all(p["object"] == "chat.completion.chunk" for p in parsed)
        assert parsed[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_prefill_decode_kv_handoff(tiny):
    """KV exported from one engine and imported into ANOTHER must continue
    greedy generation exactly as a single engine would (reference:
    prefill_decode/pd_server.py + kv_transfer connectors)."""
    from ray_tpu.llm import LLMConfig, LLMEngine, SamplingParams

    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=96, seed=3)
    single = LLMEngine(cfg)
    prompt = list(np.random.default_rng(1).integers(1, 200, 12))
    want = single.generate(prompt, SamplingParams(max_tokens=6,
                                                  temperature=0.0),
                           timeout=120)
    single.shutdown()

    pre = LLMEngine(cfg)
    dec = LLMEngine(cfg)
    try:
        payload = pre.prefill_only(prompt)
        assert payload["kv_k"].shape[2] == len(prompt)
        assert payload["first_token"] == want.token_ids[0]
        req = dec.submit_prefilled(payload,
                                   SamplingParams(max_tokens=5,
                                                  temperature=0.0))
        assert req.done.wait(120) and not req.error
        got = req.out_tokens  # [first_token, decoded...]
        assert got[0] == payload["first_token"]
        # the continuation must equal the single-engine greedy sequence
        assert got == want.token_ids[:len(got)]
        assert len(got) == 5
    finally:
        pre.shutdown()
        dec.shutdown()


def test_engine_bad_kv_payload_fails_cleanly():
    """A decode engine receiving an incompatible KV payload must fail that
    request (error surfaced, waiter woken) without leaking it in _requests
    or wedging the scheduler (engine.py _admit / _fail)."""
    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64))
    try:
        bad = {
            "prompt_ids": [1, 2, 3],
            "first_token": 5,
            # wrong layer count -> shape validation failure on import
            "kv_k": np.zeros((99, 1, 3, 4), np.float32),
            "kv_v": np.zeros((99, 1, 3, 4), np.float32),
        }
        req = eng.submit_prefilled(bad, SamplingParams(max_tokens=4))
        assert req.done.wait(60)
        assert req.error and "KV import failed" in req.error
        assert req.finish_reason == "error"
        assert req.request_id not in eng._requests
        assert req.preloaded is None  # staged payload released
        # engine still serves normal traffic afterwards
        res = eng.generate([1, 2, 3], SamplingParams(max_tokens=3,
                                                     temperature=0.0))
        assert len(res.token_ids) > 0
    finally:
        eng.shutdown()


def test_engine_recovers_from_device_failure(monkeypatch):
    """decode_step donates the KV cache, so a device-side failure kills the
    cache with it. The engine must fail in-flight requests AND rebuild the
    cache so new traffic still works (engine.py _recover_device_failure)."""
    import ray_tpu.llm.engine as eng_mod

    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64))
    real_decode = eng_mod.decode_step
    real_burst = eng_mod.decode_burst
    boom = {"n": 0}

    def flaky_decode(*a, **kw):
        if boom["n"] == 0:
            boom["n"] += 1
            raise RuntimeError("RESOURCE_EXHAUSTED (simulated)")
        return real_decode(*a, **kw)

    def flaky_burst(*a, **kw):
        if boom["n"] == 0:
            boom["n"] += 1
            raise RuntimeError("RESOURCE_EXHAUSTED (simulated)")
        return real_burst(*a, **kw)

    try:
        monkeypatch.setattr(eng_mod, "decode_step", flaky_decode)
        monkeypatch.setattr(eng_mod, "decode_burst", flaky_burst)
        req = eng.submit([1, 2, 3], SamplingParams(max_tokens=4))
        assert req.done.wait(60)
        assert req.error and "decode failed" in req.error
        # fresh cache, fresh request: engine serves normally again
        res = eng.generate([1, 2, 3], SamplingParams(max_tokens=3,
                                                     temperature=0.0))
        assert len(res.token_ids) > 0 and boom["n"] == 1
    finally:
        eng.shutdown()


def test_pd_serving_app():
    """Full P/D app through serve: prefill replica -> KV object -> decode
    replica -> ingress answer matches the single-server app (greedy)."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.pd import build_pd_openai_app
    from ray_tpu.llm.serving import build_openai_app

    body = _json.dumps({
        "messages": [{"role": "user", "content": "hello pd"}],
        "max_tokens": 5, "temperature": 0.0,
    }).encode()

    def ask(port):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return _json.loads(r.read())

    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=96, seed=5)
    ray_tpu.init()
    try:
        serve.run(build_openai_app(cfg), route_prefix="/", http=True)
        baseline = ask(serve.http_port())["choices"][0]["message"]["content"]
        serve.shutdown()

        ray_tpu.shutdown()
        ray_tpu.init()
        serve.run(build_pd_openai_app(cfg), route_prefix="/", http=True)
        pd_answer = ask(serve.http_port())
        assert pd_answer["choices"][0]["message"]["content"] == baseline
        # usage parity with the single-server OpenAI path
        u = pd_answer["usage"]
        assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]
        # streaming through the P/D path too
        sreq = urllib.request.Request(
            f"http://127.0.0.1:{serve.http_port()}/v1/chat/completions",
            data=_json.dumps({
                "messages": [{"role": "user", "content": "hello pd"}],
                "max_tokens": 4, "temperature": 0.0, "stream": True,
            }).encode(), headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(sreq, timeout=120) as r:
            text = r.read().decode()
        assert text.rstrip().endswith("data: [DONE]")
        # every chunk frame must carry id/model (strict SDK clients require
        # the same frame shape as the single-server path)
        for line in text.splitlines():
            if line.startswith("data: {"):
                frame = _json.loads(line[len("data: "):])
                assert frame["id"] and frame["model"]
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_prefix_cache_exact_rehit_zero_copy():
    """Re-submitting the same prompt adopts the retired slot's KV: only the
    final prompt token is recomputed, and greedy output is identical
    (reference: vLLM automatic prefix caching semantics)."""
    cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64)
    eng = LLMEngine(cfg)
    try:
        prompt = list(range(2, 34))  # 32 tokens
        r1 = eng.generate(prompt, SamplingParams(max_tokens=6))
        assert eng.prefix_hits == 0
        r2 = eng.generate(prompt, SamplingParams(max_tokens=6))
        assert eng.prefix_hits == 1
        assert eng.prefix_tokens_saved == len(prompt) - 1
        assert r1.token_ids == r2.token_ids
    finally:
        eng.shutdown()


def test_prefix_cache_shared_prefix_correctness():
    """A request sharing only a PREFIX with a cached prompt must produce
    exactly what a cold engine produces for the same prompt — the adopted
    KV plus the recomputed tail must be equivalent to a full prefill."""
    prefix = list(range(2, 34))            # 32 shared tokens
    prompt_b = prefix + [40, 41, 42, 43]   # diverges after the prefix

    cold = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64))
    try:
        expect = cold.generate(prompt_b, SamplingParams(max_tokens=6))
    finally:
        cold.shutdown()

    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64))
    try:
        eng.generate(prefix, SamplingParams(max_tokens=4))  # seeds the cache
        got = eng.generate(prompt_b, SamplingParams(max_tokens=6))
        assert eng.prefix_hits == 1
        assert eng.prefix_tokens_saved == len(prefix)  # capped at donor len
        assert got.token_ids == expect.token_ids
    finally:
        eng.shutdown()


def test_prefix_cache_live_donor_copy():
    """Adoption from a donor whose request is STILL RUNNING copies the KV
    line to the new slot; outputs match the cold engine."""
    import time as _t

    prefix = list(range(2, 34))
    prompt_b = prefix + [45, 46]

    cold = LLMEngine(LLMConfig(model="tiny", max_num_seqs=3, max_seq_len=96))
    try:
        expect = cold.generate(prompt_b, SamplingParams(max_tokens=5))
    finally:
        cold.shutdown()

    eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=3, max_seq_len=96))
    try:
        long_req = eng.submit(prefix, SamplingParams(max_tokens=48))
        deadline = _t.time() + 60
        while not eng._prefix_live and _t.time() < deadline:
            _t.sleep(0.01)  # wait for the donor's prefill to complete
        assert eng._prefix_live, "donor prefill never completed"
        got = eng.generate(prompt_b, SamplingParams(max_tokens=5))
        assert eng.prefix_hits >= 1
        assert got.token_ids == expect.token_ids
        long_req.done.wait(60)
    finally:
        eng.shutdown()


class TestSpeculativeDecoding:
    def test_spec_verify_matches_sequential_decode(self, tiny):
        """spec_verify_step over K tokens produces the same logits and
        cache as K sequential decode_step calls."""
        from ray_tpu.llm.engine import spec_verify_step

        cfg, params = tiny
        K = 3
        prompt = np.array([5, 7, 11, 13], np.int32)
        toks = np.array([17, 19, 23], np.int32)  # K tokens to consume
        c1 = init_kv_cache(cfg, max_slots=2, max_seq=32)
        c1, _ = prefill(cfg, params, c1, jnp.asarray(prompt),
                        jnp.int32(len(prompt)), jnp.int32(0))
        c2 = jax.tree.map(jnp.copy, c1)

        seq_logits = []
        for j, t in enumerate(toks):
            c1, lg = decode_step(
                cfg, params, c1,
                jnp.asarray([t, 0], np.int32),
                jnp.asarray([len(prompt) + j, 0], np.int32),
                jnp.asarray([True, False]))
            seq_logits.append(np.asarray(lg[0]))

        c2, logits = spec_verify_step(
            cfg, params, c2,
            jnp.asarray(np.stack([toks, np.zeros_like(toks)])),
            jnp.asarray([len(prompt), 0], np.int32),
            jnp.asarray([True, False]))
        for j in range(K):
            np.testing.assert_allclose(np.asarray(logits[0, j]),
                                       seq_logits[j], rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(c1["k"]), np.asarray(c2["k"]),
                                   rtol=1e-5, atol=1e-5)

    def test_spec_output_identical_perfect_draft(self):
        """Draft == target: outputs must match vanilla greedy exactly and
        acceptance must be (near) total."""
        from ray_tpu.models.llama import init_params as ip

        tgt_params = ip(LLMConfig(model="tiny").model_config(),
                        jax.random.PRNGKey(3))
        base = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                   max_seq_len=64), params=tgt_params)
        spec = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                   max_seq_len=64,
                                   speculative_model="tiny",
                                   speculative_tokens=3),
                         params=tgt_params)
        spec.draft_params = tgt_params  # perfect draft
        try:
            sp = SamplingParams(max_tokens=24, temperature=0.0)
            r0 = base.generate("hello tpu", sampling=sp)
            r1 = spec.generate("hello tpu", sampling=sp)
            assert r1.token_ids == r0.token_ids
            st = spec.stats()
            assert st["spec_ticks"] > 0
            assert st["spec_acceptance"] > 0.9, st
        finally:
            base.shutdown()
            spec.shutdown()

    def test_spec_output_identical_bad_draft(self):
        """The correctness invariant: a DIFFERENT (randomly-initialized)
        draft still yields exactly the vanilla greedy output — speculation
        only changes speed, never results."""
        from ray_tpu.models.llama import init_params as ip

        tgt_params = ip(LLMConfig(model="tiny").model_config(),
                        jax.random.PRNGKey(3))
        base = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                   max_seq_len=64), params=tgt_params)
        spec = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                   max_seq_len=64,
                                   speculative_model="tiny",
                                   speculative_tokens=4),
                         params=tgt_params)  # draft params: seed+7 random
        try:
            sp = SamplingParams(max_tokens=20, temperature=0.0)
            for prompt in ("abc", "speculate this"):
                r0 = base.generate(prompt, sampling=sp)
                r1 = spec.generate(prompt, sampling=sp)
                assert r1.token_ids == r0.token_ids, prompt
            st = spec.stats()
            assert st["spec_ticks"] > 0
        finally:
            base.shutdown()
            spec.shutdown()

    def test_spec_disabled_after_repeated_catchup_failure(self):
        """A request whose draft catch-up fails persistently is
        speculation-disabled after 3 attempts (bounded blast radius) —
        it still completes via plain decode, and the engine keeps
        speculating for later requests instead of staying dark."""
        eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                  max_seq_len=64,
                                  speculative_model="tiny",
                                  speculative_tokens=3))
        # Fail the victim's DRAFT prefill dispatches at the device-call
        # layer so the real _draft_catch_up except path (fail counting,
        # disable-at-3, draft-cache rebuild) is what runs — not a stub
        # re-implementing it.
        import ray_tpu.llm.engine as engine_mod
        orig_prefill = engine_mod.prefill_chunk

        def failing_prefill(cfg, params, cache, toks, start, end, slot):
            if cfg is eng.draft_cfg and \
                    eng._slots.get(int(slot)) is victim:
                raise RuntimeError("injected draft prefill failure")
            return orig_prefill(cfg, params, cache, toks, start, end, slot)

        try:
            engine_mod.prefill_chunk = failing_prefill
            # max_tokens must span >= 3 fallback ticks: each failed
            # catch-up tick now burst-decodes up to decode_burst tokens, so
            # a short request could finish before the 3rd failure disables
            # speculation.
            victim = eng.submit("doomed draft", sampling=SamplingParams(
                max_tokens=30, temperature=0.0))
            assert victim.done.wait(60) and victim.error is None
            assert victim.spec_disabled
            assert len(victim.out_tokens) == 30
            # Engine must still speculate for a healthy follow-up request.
            healthy = eng.submit("fine", sampling=SamplingParams(
                max_tokens=10, temperature=0.0))
            assert healthy.done.wait(60) and healthy.error is None
            assert not healthy.spec_disabled
            assert eng.stats()["spec_ticks"] > 0
        finally:
            engine_mod.prefill_chunk = orig_prefill
            eng.shutdown()

    def test_spec_tick_abandoned_after_plain_decode_device_failure(self):
        """Mixed tick: the plain-decode half hits a device failure, which
        fails every request and rebuilds both caches. The speculative half
        must then be abandoned — dispatching the draft against the rebuilt
        state would emit garbage into already-failed requests."""
        eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                  max_seq_len=64,
                                  speculative_model="tiny",
                                  speculative_tokens=3))
        import ray_tpu.llm.engine as engine_mod
        orig_decode = engine_mod.decode_step
        orig_burst = engine_mod.decode_burst
        orig_propose = engine_mod.draft_propose
        spec_dispatch_after_failure = []
        failed_once = []

        def both_decode_ready():
            return (plain.out_tokens and spec.out_tokens
                    and not plain.done.is_set() and not spec.done.is_set())

        def failing_decode(*a, **kw):
            # Fail only the mixed tick — when both requests decode in the
            # same tick — so the injection deterministically hits the
            # plain half of _spec_decode with the spec half pending.
            if both_decode_ready():
                failed_once.append(True)
                raise RuntimeError("injected device failure")
            return orig_decode(*a, **kw)

        def failing_burst(*a, **kw):
            if both_decode_ready():
                failed_once.append(True)
                raise RuntimeError("injected device failure")
            return orig_burst(*a, **kw)

        def recording_propose(*a, **kw):
            if failed_once:
                spec_dispatch_after_failure.append(True)
            return orig_propose(*a, **kw)

        try:
            engine_mod.decode_step = failing_decode
            engine_mod.decode_burst = failing_burst
            engine_mod.draft_propose = recording_propose
            plain = eng.submit("plain one", sampling=SamplingParams(
                max_tokens=32, temperature=0.0))
            plain.spec_disabled = True  # ride the plain half of the tick
            spec = eng.submit("spec one", sampling=SamplingParams(
                max_tokens=32, temperature=0.0))
            assert plain.done.wait(60) and spec.done.wait(60)
            assert plain.error is not None
            assert spec.error is not None
            assert not spec_dispatch_after_failure, (
                "speculative half dispatched after device recovery")
        finally:
            engine_mod.decode_step = orig_decode
            engine_mod.decode_burst = orig_burst
            engine_mod.draft_propose = orig_propose
            eng.shutdown()

    def test_spec_mixed_batch_stochastic_falls_back(self):
        """Stochastic requests ride the normal decode path while greedy
        requests speculate — both finish correctly in one engine."""
        eng = LLMEngine(LLMConfig(model="tiny", max_num_seqs=2,
                                  max_seq_len=64,
                                  speculative_model="tiny",
                                  speculative_tokens=3))
        try:
            greedy = eng.submit("aaa", sampling=SamplingParams(
                max_tokens=12, temperature=0.0))
            warm = eng.submit("bbb", sampling=SamplingParams(
                max_tokens=12, temperature=0.8, seed=1))
            assert greedy.done.wait(60) and warm.done.wait(60)
            assert greedy.error is None and warm.error is None
            assert len(greedy.out_tokens) > 0 and len(warm.out_tokens) > 0
            assert eng.stats()["spec_ticks"] > 0
        finally:
            eng.shutdown()


class TestBurstDecoding:
    """decode_burst: D chained decode+sample steps per dispatch
    (engine.py decode_burst) must be invisible to outputs."""

    def test_burst_matches_single_step_greedy(self):
        base = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64,
                         decode_burst=1)
        burst = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64,
                          decode_burst=4)
        e1, e2 = LLMEngine(base), LLMEngine(burst)
        try:
            for prompt, n in [("hello burst", 13), ("x", 3), ("abc", 8)]:
                r1 = e1.generate(prompt, SamplingParams(max_tokens=n))
                r2 = e2.generate(prompt, SamplingParams(max_tokens=n))
                assert r1.token_ids == r2.token_ids, (prompt, n)
                assert r2.finish_reason == r1.finish_reason
        finally:
            e1.shutdown()
            e2.shutdown()

    def test_burst_concurrent_isolated(self):
        """Burst ticks over a mixed batch: each request's output matches
        its solo regeneration (no cross-slot contamination inside the
        scanned steps)."""
        cfg = LLMConfig(model="tiny", max_num_seqs=4, max_seq_len=64,
                        decode_burst=8)
        eng = LLMEngine(cfg)
        try:
            results = [None] * 4
            def gen(i):
                results[i] = eng.generate(f"burst prompt {i}",
                                          SamplingParams(max_tokens=10))
            threads = [threading.Thread(target=gen, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(r is not None for r in results)
            solo = eng.generate("burst prompt 2",
                                SamplingParams(max_tokens=10))
            assert solo.token_ids == results[2].token_ids
        finally:
            eng.shutdown()

    def test_top_k_falls_back_to_single_step(self):
        """top-k sampling can't ride the burst (static k); the engine must
        still serve it correctly via single-step ticks."""
        cfg = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=64,
                        decode_burst=8)
        eng = LLMEngine(cfg)
        try:
            r = eng.generate("topk prompt", SamplingParams(
                max_tokens=6, temperature=0.8, top_k=5, seed=1))
            assert 0 < len(r.token_ids) <= 6
        finally:
            eng.shutdown()

    def test_pipelined_bursts_match_unpipelined(self):
        """Chained bursts (decode_pipeline) must be output-invisible:
        long generations where chaining engages every steady tick."""
        base = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128,
                         decode_burst=4, decode_pipeline=False)
        piped = LLMConfig(model="tiny", max_num_seqs=2, max_seq_len=128,
                          decode_burst=4, decode_pipeline=True)
        e1, e2 = LLMEngine(base), LLMEngine(piped)
        try:
            for prompt, n in [("pipeline me", 40), ("zz", 21)]:
                r1 = e1.generate(prompt, SamplingParams(max_tokens=n))
                r2 = e2.generate(prompt, SamplingParams(max_tokens=n))
                assert r1.token_ids == r2.token_ids, (prompt, n)
        finally:
            e1.shutdown()
            e2.shutdown()


def test_hf_checkpoint_conversion_numerical_parity(tmp_path):
    """convert_hf_llama vs the transformers reference implementation:
    identical logits on a tiny random-init HF Llama (layout transposes,
    RoPE convention, GQA, norms, tied embeddings all verified at once)."""
    torch = pytest.importorskip("torch")
    tfs = pytest.importorskip("transformers")

    hf_cfg = tfs.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = tfs.LlamaForCausalLM(hf_cfg).eval()

    from ray_tpu.llm.hf import convert_hf_llama
    from ray_tpu.models.llama import forward

    cfg, params = convert_hf_llama(model, dtype="float32")
    assert cfg.num_kv_heads == 2 and cfg.head_dim == 16

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, (2, 17), dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.float().numpy()
    ours = np.asarray(
        forward(cfg, params, jnp.asarray(tokens, jnp.int32), remat=False),
        np.float32)
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)

    # round-trip through a saved checkpoint directory
    model.save_pretrained(tmp_path / "ck")
    cfg2, params2 = convert_hf_llama(str(tmp_path / "ck"), dtype="float32")
    ours2 = np.asarray(
        forward(cfg2, params2, jnp.asarray(tokens, jnp.int32), remat=False),
        np.float32)
    np.testing.assert_allclose(ours2, ref, atol=2e-3, rtol=2e-3)


def test_engine_loads_hf_checkpoint_dir(tmp_path):
    """LLMConfig(checkpoint_path=<HF dir>) boots the engine with geometry
    AND weights from the checkpoint (byte-tokenizer-compatible vocab)."""
    torch = pytest.importorskip("torch")
    tfs = pytest.importorskip("transformers")

    hf_cfg = tfs.LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(0)
    tfs.LlamaForCausalLM(hf_cfg).save_pretrained(tmp_path / "hf")

    eng = LLMEngine(LLMConfig(model="tiny", dtype="float32",
                              checkpoint_path=str(tmp_path / "hf"),
                              max_num_seqs=2, max_seq_len=64))
    try:
        assert eng.model_cfg.hidden_size == 64  # geometry from checkpoint
        r = eng.generate("hi", SamplingParams(max_tokens=5))
        assert 0 < len(r.token_ids) <= 5
    finally:
        eng.shutdown()
