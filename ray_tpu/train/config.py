"""Train configuration types.

Capability parity with the reference's config surface (reference:
python/ray/train/v2/api/config.py — ScalingConfig with TPU fields topology/
accelerator_type/use_tpu :83,196-205; RunConfig/FailureConfig/CheckpointConfig
shapes from ray.air/ray.train).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    topology: str | None = None          # e.g. "4x4" → one v5p-32 slice
    accelerator_type: str | None = None  # e.g. "v5p"
    resources_per_worker: dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    # Elastic range (reference: elastic.py:29 ElasticScalingPolicy). Setting
    # either makes scaling elastic: every (re)start picks the largest
    # feasible world size in [min_workers, max_workers].
    min_workers: int | None = None
    max_workers: int | None = None
    # Hot spares: reserve TrainWorker actors the controller keeps pre-warmed
    # (process booted, framework/jax imported) OUTSIDE the group. On a
    # worker/slice failure the next group promotes them instead of paying
    # cold fork+import — the dominant cost of a restart when state comes
    # from in-cluster replicas rather than a checkpoint. On TPU fleets this
    # is the reserve-slice pattern: spares sized to one slice make a
    # whole-slice loss recoverable at full world size.
    hot_spares: int = 0
    # Optional callable run once inside every hot spare right after it
    # boots (via exec_fn): import the training stack, build the mesh,
    # compile the step — whatever makes promotion instant. Without it a
    # promoted spare still skips the fork+framework-import cost but pays
    # the train_fn's own first-use imports/compiles on its first step.
    hot_spare_warmup: Any = None

    def worker_resources(self) -> dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 4.0  # one host's chips by default
        if "CPU" not in res and not self.use_tpu:
            res["CPU"] = 1.0
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = unlimited restarts from latest checkpoint


@dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_frequency: int = 0
    # In-cluster replication cadence: every N steps session.replicate()
    # actually pushes the worker's state shards to its buddy slice's
    # ReplicaStore (train/replica.py). 0 disables replication — restarts
    # then always restore from the latest checkpoint. With it on, the
    # controller prefers the replica fast-restart tier whenever surviving
    # stores cover every rank at a step >= the newest checkpoint.
    replicate_every: int = 0


@dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    # air integration callbacks (ray_tpu.air.integrations), invoked by the
    # controller on run start / each reported result / checkpoint / run end.
    callbacks: list = field(default_factory=list)
