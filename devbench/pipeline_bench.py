"""Compiled-graph pipeline bench: zero-RPC dataflow + pipelined execution.

Emits PERF_PIPELINE.json:
- per-hop channel latency + steps/sec for the KV (head round-trip) vs
  direct (peer push) transports, and for a 1 MiB ndarray riding the
  store-backed buffer path (same-host: pinned arena views),
- control-plane RPCs per executed step, from the head's per-method inbound
  frame odometer: ~0 for direct channels (the head KV is touched once at
  compile for route exchange), vs the KV transport's put/get/del traffic,
- pipelined-vs-synchronous throughput of a 4-stage sleepy pipeline as the
  execute_async in-flight window deepens (fill/drain across steps),
- a 4-stage MPMD toy-model training step under the GPipe schedule vs a
  fully serial schedule (intra-step microbatch overlap), with the loss
  trajectory asserting the math still trains.

Gates (acceptance): direct beats KV per-hop >= 5x same-host; RPCs/step
<= 0.5 on the direct path; window depth 4 >= 3x over synchronous; GPipe
>= 3x over the serial schedule.

Run: python devbench/pipeline_bench.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RTPU_WORKER_IDLE_TTL_S", "300")

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.cluster_utils import Cluster  # noqa: E402
from ray_tpu.core.worker import global_worker  # noqa: E402
from ray_tpu.dag import InputNode  # noqa: E402
from ray_tpu.utils.ids import JobID  # noqa: E402


def pct(samples: list[float]) -> dict:
    if not samples:
        return {}
    s = sorted(samples)

    def at(q):
        return s[min(len(s) - 1, int(q * len(s)))]

    return {"n": len(s), "p50_ms": round(at(0.50) * 1e3, 3),
            "p90_ms": round(at(0.90) * 1e3, 3),
            "p99_ms": round(at(0.99) * 1e3, 3)}


@ray_tpu.remote
class Echo:
    """Identity stage: isolates channel cost from compute."""

    def f(self, x):
        return x


@ray_tpu.remote
class SleepyStage:
    """Fixed dwell per op — the portable stand-in for per-stage device
    time on a one-core box (real compute cannot overlap across local
    processes; sleep exhibits exactly the schedule overlap the pipeline
    exploits)."""

    def __init__(self, dwell_s: float):
        self.dwell_s = dwell_s

    def f(self, x):
        time.sleep(self.dwell_s)
        return x


def _setup_cluster():
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=10)
    rt = cluster.connect()
    old = (global_worker.runtime, global_worker.worker_id,
           global_worker.node_id, global_worker.mode, global_worker.job_id)
    global_worker.runtime = rt
    global_worker.worker_id = rt.worker_id
    global_worker.node_id = rt.node_id
    global_worker.job_id = JobID.from_random()
    global_worker.mode = "cluster"
    rt._daemon.call("prestart_workers", n=4, timeout=15)
    return cluster, rt, old


def _teardown(cluster, rt, old):
    rt.shutdown()
    cluster.shutdown()
    (global_worker.runtime, global_worker.worker_id, global_worker.node_id,
     global_worker.mode, global_worker.job_id) = old


def _echo_dag(stages):
    with InputNode() as inp:
        return stages[1].f.bind(stages[0].f.bind(inp))


def _kill(actors):
    """Explicit kills, then a settle: letting handles leak to GC defers the
    worker churn (kill + prestart replacement) into the NEXT phase's timed
    region — on a one-core box that skews its latencies."""
    for a in actors:
        try:
            ray_tpu.kill(a, no_restart=True)
        except Exception:
            pass
    time.sleep(1.0)


def _measure_steps(compiled, payload, n, timeout=60.0):
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        compiled.execute(payload, timeout=timeout)
        lat.append(time.perf_counter() - t0)
    return lat


def _phase_per_hop(stages, quick: bool) -> dict:
    """KV vs direct per-hop latency on a 2-stage echo chain (3 hops:
    driver->s1->s2->driver), plus the store-backed ndarray path. All
    variants recompile on the SAME two actors: loops exit at teardown and
    the next compile installs fresh schedules, with no actor churn between
    timed regions."""
    n = 30 if quick else 100
    hops = 3
    out = {}
    # Direct variants run FIRST: the KV transport's per-step head traffic
    # churns enough metrics/spans that the next periodic telemetry flush
    # burns the one core for ~2s — a cost of the KV design, so the KV
    # variant runs last and absorbs its own storm (plus a settle).
    compiled = _echo_dag(stages).experimental_compile(_channel_kind="direct")
    try:
        _measure_steps(compiled, 1, 3)  # warm routes
        lat = _measure_steps(compiled, 1, n)
    finally:
        compiled.teardown()
    out["direct_small"] = {
        **pct(lat),
        "per_hop_p50_ms": round(pct(lat)["p50_ms"] / hops, 3),
        "steps_per_s": round(n / sum(lat), 1),
    }
    # 1 MiB ndarray: above the inline threshold, so activations ride the
    # object plane as store-backed buffers (node shm arena -> the reader
    # maps a pinned view; no per-step serialization of the payload into
    # control frames).
    arr = np.ones((512, 512), np.float32)
    compiled = _echo_dag(stages).experimental_compile(_channel_kind="direct")
    try:
        _measure_steps(compiled, arr, 3)
        lat = _measure_steps(compiled, arr, max(10, n // 3))
    finally:
        compiled.teardown()
    out["direct_ndarray_1mb"] = {
        **pct(lat),
        "per_hop_p50_ms": round(pct(lat)["p50_ms"] / hops, 3),
        "steps_per_s": round(len(lat) / sum(lat), 1),
    }
    compiled = _echo_dag(stages).experimental_compile(_channel_kind="kv")
    try:
        _measure_steps(compiled, 1, 3)  # warm slots
        lat = _measure_steps(compiled, 1, n)
    finally:
        compiled.teardown()
    out["kv_small"] = {
        **pct(lat),
        "per_hop_p50_ms": round(pct(lat)["p50_ms"] / hops, 3),
        "steps_per_s": round(n / sum(lat), 1),
    }
    time.sleep(2.5)  # KV metric-churn telemetry storm off the core
    out["direct_vs_kv_per_hop"] = round(
        out["kv_small"]["per_hop_p50_ms"]
        / max(out["direct_small"]["per_hop_p50_ms"], 1e-6), 1)
    return out


def _phase_rpcs_per_step(stages, rt, quick: bool) -> dict:
    """Head inbound frames per executed step, per method. The direct path
    should add ~nothing (compile-time route exchange only); the KV path
    pays puts/gets/deletes — and its reader busy-poll — per hop."""
    n = 20 if quick else 50
    out = {}
    for kind in ("direct", "kv"):
        compiled = _echo_dag(stages).experimental_compile(_channel_kind=kind)
        try:
            _measure_steps(compiled, 1, 3)
            before = rt.head_rpc_counts()
            futs = [compiled.execute_async(i) for i in range(n)]
            for f in futs:
                f.result(60)
            after = rt.head_rpc_counts()
        finally:
            compiled.teardown()
        delta = {k: after.get(k, 0) - before.get(k, 0)
                 for k in set(after) | set(before)
                 if after.get(k, 0) != before.get(k, 0)}
        # Subtract our own probe (the post-window rpc_counts call is one
        # inbound frame) and the periodic background frames — heartbeats
        # and telemetry flushes are time-based, not per-step control plane
        # (they show up in the breakdown regardless).
        background = {"rpc_counts", "heartbeat", "report_telemetry"}
        net = sum(v for k, v in delta.items() if k not in background)
        out[kind] = {
            "steps": n,
            "head_frames_by_method": delta,
            "rpcs_per_step": round(net / n, 3),
        }
        if kind == "kv":
            time.sleep(2.5)  # the KV variant's telemetry storm, again
    return out


def _phase_window_pipelining(quick: bool) -> dict:
    """4 sleepy stages chained; synchronous execute vs execute_async with
    a deepening in-flight window. Depth d keeps d steps in the pipe, so
    throughput approaches 1/stage-dwell instead of 1/(4*dwell)."""
    dwell = 0.025
    stages = [SleepyStage.remote(dwell) for _ in range(4)]
    with InputNode() as inp:
        node = inp
        for s in stages:
            node = s.f.bind(node)
    sync_n = 8 if quick else 12
    depths = (1, 4) if quick else (1, 2, 4, 8)
    out = {"stage_dwell_ms": dwell * 1e3, "num_stages": 4}

    compiled = node.experimental_compile()
    try:
        _measure_steps(compiled, 0, 2)
        t0 = time.perf_counter()
        for i in range(sync_n):
            compiled.execute(i, timeout=60)
        sync_sps = sync_n / (time.perf_counter() - t0)
    finally:
        compiled.teardown()
    out["sync_steps_per_s"] = round(sync_sps, 2)

    out["by_depth"] = {}
    for depth in depths:
        compiled = node.experimental_compile(_max_inflight=depth)
        try:
            _measure_steps(compiled, 0, 2)
            n = max(12, 3 * depth)
            t0 = time.perf_counter()
            futs = [compiled.execute_async(i) for i in range(n)]
            for f in futs:
                f.result(60)
            sps = n / (time.perf_counter() - t0)
        finally:
            compiled.teardown()
        out["by_depth"][str(depth)] = {
            "steps_per_s": round(sps, 2),
            "speedup_vs_sync": round(sps / sync_sps, 2),
        }
    _kill(stages)
    return out


def _phase_mpmd_toy(quick: bool) -> dict:
    """4-stage MPMD toy model, one optimizer step per execution. GPipe's
    per-stage fill/drain order overlaps microbatches across stages; the
    serial schedule (each microbatch's full forward+backward round trip
    before the next) is the no-pipelining baseline on the SAME dag."""
    from ray_tpu.dag.mpmd import MPMDPipeline, make_toy_stage_factory
    from ray_tpu.dag.schedule import PipelineSchedule

    class SerialSchedule(PipelineSchedule):
        name = "serial"

        def forward_rank(self, mb, stage, num_stages, num_microbatches):
            return 1 + 2 * mb

        def backward_rank(self, mb, stage, num_stages, num_microbatches):
            return 2 + 2 * mb

    P, M = 4, 24
    dwell = 0.01
    width = 16
    rng = np.random.default_rng(0)
    x = rng.standard_normal((M, width), dtype=np.float32)
    t = rng.standard_normal((M, width), dtype=np.float32)
    out = {"stages": P, "microbatches": M, "stage_dwell_ms": dwell * 1e3}
    losses = []
    for name, sched, steps in (("serial", SerialSchedule(), 1 if quick else 2),
                               ("gpipe", "gpipe", 2 if quick else 3)):
        pipe = MPMDPipeline(make_toy_stage_factory(width=width, sleep_s=dwell),
                            num_stages=P, num_microbatches=M, schedule=sched)
        try:
            first = pipe.step(x, t, timeout=120)  # warm jits + routes
            t0 = time.perf_counter()
            for _ in range(steps):
                m = pipe.step(x, t, timeout=120)
            wall = (time.perf_counter() - t0) / steps
            if name == "gpipe":
                losses = [first["loss"], m["loss"]]
        finally:
            pipe.shutdown()  # kills the stage actors too
        time.sleep(1.0)  # settle: replacement-worker prestart off the core
        out[name] = {"step_wall_s": round(wall, 3),
                     "steps_measured": steps}
    out["gpipe_speedup_vs_serial"] = round(
        out["serial"]["step_wall_s"] / max(out["gpipe"]["step_wall_s"], 1e-9),
        2)
    out["loss_first"] = round(losses[0], 5)
    out["loss_later"] = round(losses[1], 5)
    out["loss_decreased"] = losses[1] < losses[0]
    return out


def run_bench(quick: bool = False, out_path: str | None = None) -> dict:
    from ray_tpu.utils import config as config_mod

    config_mod.set_config(config_mod.Config.load())
    cluster, rt, old = _setup_cluster()
    try:
        echoes = [Echo.remote(), Echo.remote()]
        per_hop = _phase_per_hop(echoes, quick)
        rpcs = _phase_rpcs_per_step(echoes, rt, quick)
        _kill(echoes)
        window = _phase_window_pipelining(quick)
        mpmd = _phase_mpmd_toy(quick)
    finally:
        _teardown(cluster, rt, old)

    depth4 = window["by_depth"].get("4", {})
    acceptance = {
        "direct_beats_kv_5x_per_hop": per_hop["direct_vs_kv_per_hop"] >= 5.0,
        "rpcs_per_step_near_zero": rpcs["direct"]["rpcs_per_step"] <= 0.5,
        "pipelined_speedup_ge_3x_depth4":
            depth4.get("speedup_vs_sync", 0.0) >= 3.0,
        "mpmd_gpipe_speedup_ge_3x": mpmd["gpipe_speedup_vs_serial"] >= 3.0,
        "mpmd_loss_decreases": mpmd["loss_decreased"],
    }
    report = {
        "bench": "pipeline",
        "quick": quick,
        "phases": {
            "per_hop": per_hop,
            "rpcs_per_step": rpcs,
            "window_pipelining": window,
            "mpmd_toy": mpmd,
        },
        "acceptance": acceptance,
        "provenance": {
            "date": time.strftime("%Y-%m-%d %H:%M:%S"),
            "cpus": os.cpu_count(),
            "loadavg": list(os.getloadavg()),
            "box_note": (
                "single host, one physical core: per-stage device dwell is "
                "emulated with sleeps (compute cannot overlap across local "
                "processes), so the speedups measure exactly what the "
                "executor provides — schedule overlap. Channel latencies "
                "and head-frame counts are real."),
        },
    }
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PERF_PIPELINE.json")
    # Quick dryrun refreshes land under "quick_refresh", never overwriting
    # full-run provenance (same contract as the other PERF files).
    doc = report
    if quick and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
            if not existing.get("quick"):
                existing["quick_refresh"] = report
                doc = existing
        except Exception:
            pass
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    return report


if __name__ == "__main__":
    rep = run_bench(quick="--quick" in sys.argv[1:])
    print(json.dumps(rep, indent=2))
    sys.exit(0 if all(rep["acceptance"].values()) else 1)
