"""Shared test helpers (importable: pytest inserts tests/ on sys.path in
this rootdir layout, so test modules use ``from _test_util import ...``)."""

import os


def load_factor() -> float:
    """Deadline multiplier gated on actual scheduler pressure, not wall
    clock: under a loaded full-suite run on a small box (1-min loadavg well
    above the core count) daemon forks, worker boots, and background GC
    chains serialize behind unrelated work, so every readiness/poll
    deadline stretches. Capped so a pathological loadavg can't turn a real
    hang into an hour-long wait."""
    try:
        per_core = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
    except OSError:
        return 1.0
    return min(max(per_core, 1.0), 4.0)
