"""State API: programmatic listing of cluster entities.

Capability parity with the reference's state API (reference:
python/ray/util/state/api.py — list_tasks/list_actors/list_objects/list_nodes/
list_workers/list_placement_groups + summarize_*, fed by GCS GcsTaskManager
and the GCS tables): entity listings with client-side filters. Filters are
``(key, op, value)`` triples with ops ``=``/``!=``, matching the reference's
filter surface.

Tasks come from this process's task-event buffer (the owner records every task
it submitted — in cluster mode that is the driver's view; node-wide events are
on each worker). Everything else comes from the runtime's state snapshot
(single source of truth: the head's tables in cluster mode).
"""

from __future__ import annotations

from typing import Any

from ray_tpu.core.worker import global_worker


def _snapshot(parts: list | None = None) -> dict:
    """``parts`` scopes the fetch to the named head tables (["nodes"],
    ["actors"], ...) — a single-entity listing at 1000 nodes must not pay
    for serializing tables it throws away."""
    global_worker.check_connected()
    try:
        return global_worker.runtime.state_snapshot(parts=parts)
    except TypeError:
        # Runtime predating the parts kwarg (test doubles): full dump.
        return global_worker.runtime.state_snapshot()


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op == "=":
                ok = str(have) == str(value)
            elif op == "!=":
                ok = str(have) != str(value)
            else:
                raise ValueError(f"unsupported filter op {op!r} (use '=' or '!=')")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def node_summary() -> dict:
    """Aggregate node view — counts + cluster resource totals in an O(1)
    payload regardless of fleet size (the cheap path `ray_tpu status`
    uses at 1000 nodes instead of a full list_nodes)."""
    global_worker.check_connected()
    return global_worker.runtime.node_summary()


def list_nodes(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot(parts=["nodes"])
    rows = [
        {"node_id": nid, **info} for nid, info in snap.get("nodes", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot(parts=["actors"])
    rows = [
        {"actor_id": aid, **info} for aid, info in snap.get("actors", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot(parts=["placement_groups"])
    rows = [
        {"placement_group_id": pid, **info}
        for pid, info in snap.get("placement_groups", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_workers(filters=None, limit: int = 10_000) -> list[dict]:
    snap = _snapshot(parts=["workers"])
    rows = [
        {"worker_id": wid, **info} for wid, info in snap.get("workers", {}).items()
    ]
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 10_000) -> list[dict]:
    """Object-store summary rows (per-store aggregate, not per-object — the
    reference's per-object listing needs the owner scan; aggregate stats serve
    the same memory-debugging purpose here)."""
    snap = _snapshot()
    stats = snap.get("objects", {})
    return _apply_filters([{"store": "local", **stats}], filters)[:limit]


def list_tasks(filters=None, limit: int = 10_000) -> list[dict]:
    """Latest state per task, merging this process's events with the
    cluster-wide events workers flushed to the head (cluster mode)."""
    from ray_tpu.core.events import all_events

    latest: dict[str, dict] = {}
    for ev in sorted(all_events(), key=lambda e: e.ts):
        row = latest.setdefault(ev.task_id, {
            "task_id": ev.task_id, "name": ev.name, "state": ev.state,
            "worker_id": ev.worker_id, "actor_id": ev.actor_id,
            "job_id": ev.job_id, "start_ts": None, "end_ts": None,
        })
        row["state"] = ev.state
        row["name"] = ev.name or row["name"]
        row["worker_id"] = ev.worker_id or row["worker_id"]
        if ev.state == "RUNNING":
            row["start_ts"] = ev.ts
        elif ev.state in ("FINISHED", "FAILED", "CANCELLED"):
            row["end_ts"] = ev.ts
    rows = list(latest.values())
    return _apply_filters(rows, filters)[:limit]


def summarize_tasks() -> dict[str, Any]:
    """Counts by (name, state) — reference: summarize_tasks."""
    summary: dict[str, dict[str, int]] = {}
    for row in list_tasks():
        by_state = summary.setdefault(row["name"], {})
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    return summary


def list_flight_records(kind: str | None = None) -> list[dict]:
    """Debug bundles dumped by the failure flight recorder on this host
    (task failures, worker deaths, actor deaths), oldest first. Each row
    has ``name``/``path``/``kind``/``ts_ns``; load one with
    ``get_flight_record(name)``."""
    from ray_tpu.core import flight_recorder

    rows = flight_recorder.list_records()
    if kind:
        rows = [r for r in rows if r["kind"] == kind]
    return rows


def get_flight_record(name: str) -> dict:
    """Load one flight-recorder bundle: the failure's context ids plus the
    last-N task events, finished spans, and a metrics snapshot captured at
    failure time."""
    from ray_tpu.core import flight_recorder

    return flight_recorder.get_record(name)


def _reject_thin_client(rt, what: str) -> None:
    """A ``client://`` runtime is attached to a REAL cluster but proxies
    only the task/object API — the in-process degrade path would silently
    profile just the local CLI process while claiming success. Error
    instead of mis-scoping."""
    try:
        from ray_tpu.util.client.client import ClientRuntime
    except Exception:
        return
    if isinstance(rt, ClientRuntime):
        raise ValueError(
            f"{what} is not available over a client:// connection; "
            "attach with address='<head-host:port>' instead")


def profile_cluster(seconds: float = 5.0, sample_hz: float = 0.0,
                    out_dir: str | None = None) -> dict:
    """On-demand cluster profile: every daemon/worker captures stack
    samples + a guarded XLA trace + a memory snapshot for ``seconds``; the
    result merges with the span timeline into one chrome-trace and one
    fleet flamegraph. In-process runtimes degrade to profiling this
    process. With ``out_dir``, artifacts are written there and their paths
    returned under ``"paths"``. The returned captures omit the raw
    ``sample_events``/span lists — they are already encoded in
    ``chrome_trace`` and would double a multi-MB payload (the ``out_dir``
    trace file holds the complete merge)."""
    from ray_tpu.profiling import (
        capture_profile,
        merge_chrome_trace,
        merge_flamegraph,
        write_artifacts,
    )
    from ray_tpu.util import tracing

    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "profile_cluster")
    if hasattr(rt, "profile_cluster"):
        res = rt.profile_cluster(seconds, sample_hz=sample_hz)
    else:
        cap = capture_profile(seconds, sample_hz=sample_hz or None,
                              meta={"kind": "driver", "source": "local"})
        res = {"captures": [] if cap.get("error") else [cap],
               "errors": ({"local": cap["reason"]} if cap.get("error")
                          else {}),
               "spans": tracing.export()}
    captures = res.get("captures") or []
    spans = res.get("spans") or []
    out = {
        "captures": [{k: v for k, v in c.items() if k != "sample_events"}
                     for c in captures],
        "errors": res.get("errors") or {},
        "chrome_trace": merge_chrome_trace(captures, spans),
        "flamegraph": merge_flamegraph(captures),
    }
    if out_dir:
        out["paths"] = write_artifacts(res, out_dir,
                                       trace=out["chrome_trace"],
                                       flame=out["flamegraph"])
    return out


def inject_chaos(rules: list | None = None, clear: bool = False) -> dict:
    """Install (or, with ``clear=True``, remove) fault-injection rules —
    fleet-wide on a cluster runtime (head fans to every daemon and worker),
    or into this process for in-process runtimes. Rule schema:
    :mod:`ray_tpu.chaos.injector`. Returns per-target injector status."""
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "inject_chaos")
    if hasattr(rt, "chaos_cluster"):
        return rt.chaos_cluster(rules=rules, clear=clear)
    from ray_tpu.chaos import injector

    if clear:
        injector.clear()
    if rules:
        injector.install(rules, replace=False)
    return {"local": injector.status()}


def chaos_status() -> dict:
    """Current chaos rules + firing log (fleet-wide on a cluster)."""
    return inject_chaos(rules=None, clear=False)


def get_stack(worker_id: str = "") -> dict:
    """Thread stacks of one worker (id or unique id prefix), or of THIS
    process when ``worker_id`` is empty — the `ray stack` capability."""
    from ray_tpu.profiling.sampler import dump_stacks

    if not worker_id:
        import os

        return {"worker_id": "local", "pid": os.getpid(),
                "stacks": dump_stacks()}
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "per-worker stacks")
    if not hasattr(rt, "dump_worker_stack"):
        raise ValueError("per-worker stacks require cluster mode "
                         "(pass no worker for a local dump)")
    matches = [w["worker_id"] for w in list_workers()
               if w["worker_id"].startswith(worker_id)]
    if not matches:
        raise ValueError(f"no worker matches {worker_id!r}")
    if len(matches) > 1:
        raise ValueError(f"ambiguous worker prefix {worker_id!r}: "
                         f"{[m[:16] for m in matches]}")
    return rt.dump_worker_stack(matches[0])


def stack_cluster() -> dict:
    """Thread stacks of EVERY process in the cluster (each node's daemon
    plus its workers) — the fleet `stack` verb with no target. In-process
    runtimes degrade to this process."""
    import os

    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "stack_cluster")
    if hasattr(rt, "stack_cluster"):
        return rt.stack_cluster()
    from ray_tpu.profiling.sampler import dump_stacks

    return {"nodes": {"local": {
        "node_id": "local",
        "daemon": {"pid": os.getpid(), "stacks": dump_stacks()},
        "workers": {}, "errors": {}}}}


def device_memory() -> dict:
    """Per-node device/host memory snapshots (live jax buffer bytes per
    device, RSS, shm-arena/object-store occupancy). In-process runtimes
    degrade to this process's snapshot."""
    from ray_tpu.profiling import memory_snapshot

    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "device_memory")
    if hasattr(rt, "device_memory"):
        return rt.device_memory()
    return {"nodes": {"local": {"node_id": "local",
                                "daemon": memory_snapshot(),
                                "workers": {}, "errors": {}}}}


def stragglers(threshold: float = 1.15) -> dict:
    """Straggler report: workers ranked by median step time vs the fleet,
    attributed compute-bound vs collective-wait, lagging host named. Feeds
    off the per-rank deciles the telemetry pushes stream to the head; the
    in-process runtime reads this process's train contexts directly."""
    import time as _time

    from ray_tpu.profiling import build_report

    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "stragglers")
    if hasattr(rt, "train_stats"):
        sources = rt.train_stats().get("sources", {})
    else:
        from ray_tpu.train.session import collect_train_stats

        stats = collect_train_stats()
        sources = {"local": {"node_id": "local", "ts": _time.time(),
                             "stats": stats}} if stats else {}
    return build_report(sources, threshold=threshold)


def incidents(since: float = 0.0, limit: int = 100,
              incident_id: str | None = None) -> list[dict]:
    """Health-watchdog incidents: anomalies the head detected on its
    rolling hot-path series, each with the implicated entity, the
    offending series window, a flight-record path, and the targeted
    profile summary. Cluster mode reads the head's bounded incident deque;
    in-process runtimes have no watchdog and return []."""
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "incidents")
    if not hasattr(rt, "incidents"):
        return []
    return rt.incidents(since=since, limit=limit,
                        incident_id=incident_id).get("incidents", [])


def timeseries(name: str | None = None, source: str | None = None,
               node_id: str | None = None, tags: dict | None = None,
               since: float = 0.0, max_points: int = 0,
               max_age_s: float = 0.0) -> list[dict]:
    """Rolling hot-path series from the head's watchdog store (train step
    time / tokens/s / MFU, collective latency+bytes, serve TTFT/TPOT/queue/
    shed, transfer bytes, per-process RSS/HBM, node heartbeat gaps).
    ``name`` matches exactly, or as a prefix with a trailing ``*``.
    In-process runtimes return []."""
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "timeseries")
    if not hasattr(rt, "get_timeseries"):
        return []
    return rt.get_timeseries(name=name, source=source, node_id=node_id,
                             tags=tags, since=since, max_points=max_points,
                             max_age_s=max_age_s).get("series", [])


def get_goodput(run: str | None = None) -> dict:
    """Fleet goodput ledger rollup: per-run and fleet goodput % with the
    badput breakdown in chip-seconds (compile, input_wait, collective_wait,
    checkpoint, replication_push, restart_downtime, head_outage, idle),
    unattributed residual, and the serve request-goodput leg. ``run``
    filters the per-run section. In-process runtimes have no head rollup
    and report disabled."""
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "goodput")
    if not hasattr(rt, "get_goodput"):
        return {"enabled": False, "runs": {}, "fleet": {}, "serve": {},
                "note": "in-process runtime (no head rollup)"}
    return rt.get_goodput(run=run)


def head_status() -> dict:
    """Control-plane session facts: head incarnation, boot id, uptime,
    restart count, and the fault-tolerance odometers (dedup table size,
    torn-WAL-tail drops, fenced registrations, reconcile repairs).
    In-process runtimes have no separate head and report themselves."""
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "head_status")
    if not hasattr(rt, "head_status"):
        return {"incarnation": 1, "restart_count": 0,
                "note": "in-process runtime (no separate head)"}
    return rt.head_status()


def watchdog_status() -> dict:
    """Watchdog health: rule list, store occupancy, incidents, cumulative
    eval seconds (duty-cycle numerator)."""
    global_worker.check_connected()
    rt = global_worker.runtime
    _reject_thin_client(rt, "watchdog_status")
    if not hasattr(rt, "watchdog_status"):
        return {"enabled": False, "note": "in-process runtime"}
    return rt.watchdog_status()


def list_logs(node_id: str | None = None) -> list[dict]:
    """Per-node worker log files (reference: `ray logs` listing via the
    dashboard agent). Cluster mode only; in-process runtimes have no
    worker processes and return []."""
    global_worker.check_connected()
    rt = global_worker.runtime
    peer = getattr(rt, "_peer", None)
    if peer is None:
        return []
    out: list[dict] = []
    for node in list_nodes():
        if node_id and node["node_id"] != node_id:
            continue
        if not node.get("alive"):
            continue
        try:
            res = peer(tuple(node["addr"])).call("list_logs")
            out.extend(res.get("logs", []))
        except Exception:  # noqa: BLE001 - dead daemon: skip its logs
            continue
    return out


def get_log(filename: str, node_id: str, tail_bytes: int = 65536) -> str:
    """Tail of one worker log file on one node (reference: `ray logs
    <file> --node-id ...`)."""
    global_worker.check_connected()
    rt = global_worker.runtime
    peer = getattr(rt, "_peer", None)
    if peer is None:
        raise ValueError("log access requires cluster mode")
    for node in list_nodes():
        if node["node_id"] == node_id:
            if not node.get("alive"):
                raise ValueError(f"node {node_id!r} is not alive")
            res = peer(tuple(node["addr"])).call(
                "tail_log", filename=filename, tail_bytes=tail_bytes)
            if res.get("error"):
                raise FileNotFoundError(res["error"])
            return res["data"]
    raise ValueError(f"unknown node {node_id!r}")
