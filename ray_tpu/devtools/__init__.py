"""rtlint: framework-aware static analysis for ray_tpu (reference:
absl thread-annotations GUARDED_BY + clang-tidy, rebuilt for the bug
classes this codebase has actually shipped and hand-caught in review —
see CHANGES.md PR 5/8/12).

Rules (each reproduced as a fixture under tests/fixtures/rtlint/):

- R0 style: unused module-scope imports (pyflakes F401 subset; __init__.py
  re-export modules are exempt).
- R1 shared-state race: attributes mutated from more than one inferred
  thread entry point (threading.Thread targets, async RPC handlers /
  event-loop callbacks, executor submissions) without a lock held, plus
  the non-atomic read-modify-write detector (``self.x += 1`` on a shared
  attribute — the PR-12 ActorHandle.seq_no bug). Driven by the
  :func:`guarded_by` annotation convention.
- R2 lock-order: cycles in the with-statement lock-acquisition graph, and
  ``await`` while holding a *threading* lock inside ``async def``.
- R3 event-loop blocking: ``time.sleep`` / sync ``RpcClient.call`` /
  ``ray_tpu.get`` / file I/O / ``Future.result`` inside ``async def``
  bodies (the PR-5 jax-backend-init-in-the-wrong-process class rides
  here too: ``jax.devices()``/backend init calls in loop context).
- R4 metrics hygiene: duplicate metric-name registration across call
  sites (the PR-8 stranded-increments bug), ``node_id`` tag keys
  (reserved for head federation, PR 9), and unbound per-call tag merges
  on declared hot paths where ``Metric.bound()`` exists (PR 12).
- R5 knob registry: every ``RTPU_*`` env read must resolve to a Config
  field or a registry entry in utils/config.py, and attribute reads off
  ``get_config()`` must name real Config fields.

Usage: ``python -m ray_tpu lint [paths...]`` (exit 1 on unallowlisted
findings), or :func:`run_lint` from code. True-but-accepted findings live
in ``ray_tpu/devtools/rtlint_allow.txt`` with per-entry justifications.
"""

from ray_tpu.devtools.annotations import guarded_by, loop_confined

__all__ = ["guarded_by", "loop_confined", "run_lint", "LintResult",
           "Finding", "format_findings"]

_ENGINE_EXPORTS = ("run_lint", "LintResult", "Finding", "format_findings")


def __getattr__(name):
    # The annotations must stay zero-cost: every hot-path module imports
    # them, so the analyzer itself (engine/model/rules) loads lazily,
    # only when someone actually lints.
    if name in _ENGINE_EXPORTS:
        from ray_tpu.devtools import engine

        return getattr(engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
