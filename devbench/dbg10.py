import jax, jax.numpy as jnp, numpy as np
from jax import lax
NEG_INF=-1e30
rng = np.random.default_rng(0)
B,H,S,D,KB = 2,4,2048,64,512
q = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
k = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
v = jnp.asarray(rng.standard_normal((B,H,S,D)), jnp.bfloat16)
nb = S // KB
kb = k.reshape(B,H,nb,KB,D).transpose(2,0,1,3,4)
vb = v.reshape(B,H,nb,KB,D).transpose(2,0,1,3,4)
scale = 1.0/np.sqrt(D)

# stage 1: s blocks as explicit input
def from_s(sblocks, vb):
    def step(carry, inputs):
        o, m, l = carry
        s, vblk = inputs
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk).astype(jnp.float32)
        return (o_new, m_new, l_new), None
    o0 = jnp.zeros((B,H,S,D), jnp.float32)
    m0 = jnp.full((B,H,S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B,H,S), jnp.float32)
    (o, m, l), _ = lax.scan(step, (o0,m0,l0), (sblocks, vb))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(jnp.bfloat16)

sblocks = jnp.stack([ (jnp.einsum("bhqd,bhkd->bhqk", q, kb[j]).astype(jnp.float32) * scale) for j in range(nb)])
val, gs = jax.jit(jax.value_and_grad(lambda s: from_s(s, vb).astype(jnp.float32).sum()))(sblocks)
print("ds: nan:", bool(jnp.isnan(gs).any()), "max|ds|:", float(jnp.abs(gs).max()), "min/max s:", float(sblocks.min()), float(sblocks.max()), flush=True)
# then dq from ds
ds_bf = gs.astype(jnp.bfloat16)
print("ds_bf16 nan:", bool(jnp.isnan(ds_bf.astype(jnp.float32)).any()), flush=True)
dq = sum(jnp.einsum("bhqk,bhkd->bhqd", ds_bf[j], kb[j]) for j in range(nb))
print("dq nan:", bool(jnp.isnan(dq.astype(jnp.float32)).any()), flush=True)
