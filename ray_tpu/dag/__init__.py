from ray_tpu.dag.communicator import (
    Communicator,
    get_accelerator_communicator,
    register_accelerator_communicator,
)
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "DAGNode",
    "InputNode",
    "ClassMethodNode",
    "MultiOutputNode",
    "Communicator",
    "register_accelerator_communicator",
    "get_accelerator_communicator",
]
