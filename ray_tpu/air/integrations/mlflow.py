"""MLflow integration (reference: python/ray/air/integrations/mlflow.py
MLflowLoggerCallback/setup_mlflow). mlflow is not part of this image; the
callback degrades to an informative error at construction.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.air.integrations.base import Callback


def _import_mlflow():
    try:
        import mlflow  # noqa: F401
        return mlflow
    except ImportError as e:
        raise ImportError(
            "mlflow is not installed in this environment; use "
            "JsonLoggerCallback/CSVLoggerCallback/TBXLoggerCallback, or "
            "install mlflow where permitted.") from e


class MLflowLoggerCallback(Callback):
    def __init__(self, experiment_name: str | None = None,
                 tracking_uri: str | None = None, **kw):
        self._mlflow = _import_mlflow()
        self.experiment_name, self.tracking_uri, self.kw = (
            experiment_name, tracking_uri, kw)

    def on_run_start(self, run_name: str, config: dict | None) -> None:
        if self.tracking_uri:
            self._mlflow.set_tracking_uri(self.tracking_uri)
        if self.experiment_name:
            self._mlflow.set_experiment(self.experiment_name)
        self._mlflow.start_run(run_name=run_name)
        if config:
            self._mlflow.log_params(
                {k: str(v)[:250] for k, v in config.items()})

    def on_result(self, metrics: dict, iteration: int) -> None:
        self._mlflow.log_metrics(
            {k: v for k, v in metrics.items() if isinstance(v, (int, float))},
            step=iteration)

    def on_run_end(self, result: Any) -> None:
        self._mlflow.end_run()


def setup_mlflow(config: dict | None = None, **kw):
    """Per-worker setup inside a train loop (reference: setup_mlflow)."""
    return _import_mlflow()
