"""Job submission: manager, supervisor actor, REST + SDK round-trip.

Mirrors the reference's job tests (reference:
python/ray/dashboard/modules/job/tests/test_job_manager.py — submit/status
transitions, logs, stop, failed entrypoints).
"""

import sys
import time

import pytest

from ray_tpu.job_submission import JobManager, JobStatus, JobSubmissionClient


def _wait_status(mgr, sid, statuses, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = mgr.get_job_status(sid)
        if st in statuses:
            return st
        time.sleep(0.1)
    raise AssertionError(f"job {sid} stuck in {mgr.get_job_status(sid)}")


class TestJobManager:
    def test_successful_job(self, rt_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=f"{sys.executable} -c \"print('job says hi')\"")
        assert _wait_status(mgr, sid, JobStatus.TERMINAL) == JobStatus.SUCCEEDED
        assert "job says hi" in mgr.get_job_logs(sid)
        info = mgr.get_job_info(sid)
        assert info["returncode"] == 0
        assert info["entrypoint"].endswith("\"print('job says hi')\"")

    def test_failed_job(self, rt_start):
        mgr = JobManager()
        sid = mgr.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
        assert _wait_status(mgr, sid, JobStatus.TERMINAL) == JobStatus.FAILED
        assert mgr.get_job_info(sid)["returncode"] == 3

    def test_stop_job(self, rt_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
        _wait_status(mgr, sid, (JobStatus.RUNNING,))
        assert mgr.stop_job(sid)
        assert _wait_status(mgr, sid, JobStatus.TERMINAL) == JobStatus.STOPPED

    def test_env_vars_and_metadata(self, rt_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=(f"{sys.executable} -c "
                        "\"import os; print('VAR=' + os.environ['JOBVAR'])\""),
            runtime_env={"env_vars": {"JOBVAR": "zzz"}},
            metadata={"owner": "tests"},
        )
        assert _wait_status(mgr, sid, JobStatus.TERMINAL) == JobStatus.SUCCEEDED
        assert "VAR=zzz" in mgr.get_job_logs(sid)
        assert mgr.get_job_info(sid)["metadata"] == {"owner": "tests"}

    def test_duplicate_id_rejected(self, rt_start):
        mgr = JobManager()
        sid = mgr.submit_job(entrypoint="true", submission_id="dup-1")
        with pytest.raises(ValueError):
            mgr.submit_job(entrypoint="true", submission_id="dup-1")
        _wait_status(mgr, sid, JobStatus.TERMINAL)

    def test_delete_requires_terminal(self, rt_start):
        mgr = JobManager()
        sid = mgr.submit_job(
            entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
        _wait_status(mgr, sid, (JobStatus.RUNNING,))
        with pytest.raises(RuntimeError):
            mgr.delete_job(sid)
        mgr.stop_job(sid)
        _wait_status(mgr, sid, JobStatus.TERMINAL)
        assert mgr.delete_job(sid)
        with pytest.raises(ValueError):
            mgr.get_job_info(sid)

    def test_list_jobs(self, rt_start):
        mgr = JobManager()
        a = mgr.submit_job(entrypoint="true")
        b = mgr.submit_job(entrypoint="true")
        ids = {j["submission_id"] for j in mgr.list_jobs()}
        assert {a, b} <= ids
        for sid in (a, b):
            _wait_status(mgr, sid, JobStatus.TERMINAL)


class TestJobRestAndSdk:
    def test_sdk_roundtrip(self, rt_start):
        from ray_tpu.dashboard.http_server import DashboardServer

        srv = DashboardServer()
        host, port = srv.start()
        try:
            mgr = JobManager()
            mgr.attach_http(srv)
            client = JobSubmissionClient(f"http://{host}:{port}")
            sid = client.submit_job(
                entrypoint=f"{sys.executable} -c \"print('via sdk')\"",
                metadata={"via": "sdk"})
            assert client.wait_until_status(
                sid, JobStatus.TERMINAL, timeout=30) == JobStatus.SUCCEEDED
            assert "via sdk" in client.get_job_logs(sid)
            assert any(j["submission_id"] == sid for j in client.list_jobs())
            assert client.delete_job(sid)
        finally:
            srv.stop()
