"""Pipeline parallelism: GPipe-style microbatching over the ``pp`` mesh axis.

TPU-native design (SURVEY.md §2.4 PP row — the reference delegates PP to
vLLM's ``pipeline_parallel_size``, vllm_models.py:230, with stages as
separate worker processes over NCCL p2p): here the WHOLE pipeline is one
compiled SPMD program. Layer parameters are sharded over ``pp`` on their
stacked-layer axis, so each mesh slice holds its stage's layers; a
``lax.scan`` steps the GPipe schedule and hands activations to the next
stage with ``lax.ppermute`` over ICI. Autodiff through the scan + ppermute
yields the reverse pipeline schedule for the backward pass — no hand-written
stage actors, no p2p runtime.

Schedule: M microbatches, P stages, M + P - 1 ticks. At tick t, stage k
processes microbatch t - k (garbage flows through the bubble ticks and is
masked out of the loss). Loss is computed on the last stage and psum'd.

The complementary MPMD form — each stage its own actor with its own jitted
programs, activations over compiled-graph channels, for models too big for
one slice/program — is ``ray_tpu/dag/mpmd.py``; tests/test_mpmd.py pins the
two to loss parity on identical batches.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
# shard_map via the collective backend's jax-version compat shim (jax >= 0.6
# exports jax.shard_map; older releases spell it experimental + check_rep).
from ray_tpu.collective.xla_backend import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import (
    LlamaConfig,
    _layer,
    init_params,
    rms_norm,
    rope_frequencies,
)
from ray_tpu.train.spmd import TrainState, _opt_shardings


def pp_param_shardings(cfg: LlamaConfig, mesh: Mesh) -> dict:
    """Layers shard over pp on the stacked-L axis; embeddings/norms
    replicate (stage 0 / last stage use them; grads psum over pp)."""
    layer_spec = NamedSharding(mesh, P("pp"))
    repl = NamedSharding(mesh, P())
    sh = {
        "embed_tokens": repl,
        "final_norm": repl,
        "layers": {k: layer_spec for k in
                   ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "attn_norm", "mlp_norm")},
    }
    if not cfg.tie_embeddings:
        sh["lm_head"] = repl
    return sh


def make_pp_train_step(
    cfg: LlamaConfig,
    mesh: Mesh,
    num_microbatches: int,
    optimizer: optax.GradientTransformation | None = None,
    attn_impl: str = "blockwise",
    seed: int = 0,
) -> tuple[Callable, Callable, Callable]:
    """Pipeline-parallel train-step factory. The mesh must have a ``pp``
    axis (>1) and may combine ``dp`` (batch shards run identical pipelines,
    grads allreduce over dp). Returns (step_fn, init_state, data_sharder)
    matching make_train_step's contract."""
    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    M = num_microbatches
    assert cfg.num_layers % pp == 0, "num_layers must divide pp"
    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.1)

    param_sh = pp_param_shardings(cfg, mesh)
    batch_sh = NamedSharding(mesh, P("dp"))
    layer_spec = P("pp")
    repl = P()

    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    def stage_loss(embed, final_norm, lm_head, local_layers, tokens, targets):
        """Runs inside shard_map over (pp, dp). tokens/targets: [B_local, S]
        (dp shard, replicated over pp). local_layers: this stage's [L/pp,…]
        slice. Returns (nll_sum, count) — psum'd by the caller."""
        b, s = tokens.shape
        assert b % M == 0, "local batch must divide num_microbatches"
        mb = b // M
        rank = lax.axis_index("pp")
        tok_m = tokens.reshape(M, mb, s)
        tgt_m = targets.reshape(M, mb, s)
        positions = jnp.arange(s)

        head = embed.T if cfg.tie_embeddings else lm_head

        def run_stage(x):
            def body(x, lp):
                return _layer(cfg, x, lp, inv_freq, positions,
                              attn_impl, None), None
            out, _ = lax.scan(body, x, local_layers)
            return out

        fwd_perm = [(i, i + 1) for i in range(pp - 1)]

        def tick(carry, t):
            x_in, nll_sum, cnt = carry
            # Stage 0 injects microbatch t (clamped during drain ticks).
            inject = embed[tok_m[jnp.minimum(t, M - 1)]]
            x = jnp.where(rank == 0, inject, x_in)
            x = run_stage(x)
            # Last stage: microbatch t - (pp-1) finished — take its loss.
            mb_idx = t - (pp - 1)
            valid = (rank == pp - 1) & (mb_idx >= 0) & (mb_idx < M)
            tgt = tgt_m[jnp.clip(mb_idx, 0, M - 1)]
            xn = rms_norm(x, final_norm, cfg.norm_eps)
            logits = jnp.einsum("bsh,hv->bsv", xn, head,
                                preferred_element_type=jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
            w = jnp.where(valid, 1.0, 0.0)
            nll_sum = nll_sum + nll.sum() * w
            cnt = cnt + nll.size * w
            # Hand activations to the next stage for the next tick.
            x_next = lax.ppermute(x, "pp", fwd_perm)
            return (x_next, nll_sum, cnt), None

        x0 = jnp.zeros((mb, s, cfg.hidden_size), embed.dtype)
        (_, nll_sum, cnt), _ = lax.scan(
            tick, (x0, jnp.float32(0.0), jnp.float32(0.0)),
            jnp.arange(M + pp - 1))
        return nll_sum, cnt

    def local_loss_and_grads(params, tokens, targets):
        """shard_map body: returns (loss, grads) with explicit reductions —
        layer grads are stage-local (pp-sharded), shared-param grads psum
        over pp; everything psums over dp."""
        lm_head = params.get("lm_head")
        # Static global token count: normalize LOCALLY inside the grad. A
        # psum inside the differentiated function would double-count —
        # psum's transpose is psum, so each device's cotangent would be
        # scaled by the axis size (grads came out exactly pp× too large).
        total_tokens = tokens.size * dp

        def scalar_loss(p):
            nll, _cnt = stage_loss(
                p["embed_tokens"], p["final_norm"], p.get("lm_head"),
                p["layers"], tokens, targets)
            return nll / total_tokens  # this device's share of the mean

        loss_local, grads = jax.value_and_grad(scalar_loss)(params)
        loss = lax.psum(loss_local, ("pp", "dp"))  # reporting only
        # Reductions the scalar psum does not imply for param cotangents
        # under check_vma=False: shared (replicated) params are used
        # divergently per stage, so their grads must sum across pp; every
        # grad sums across dp (data parallel).
        def reduce_grad(path_is_layer, g):
            axes = ("dp",) if path_is_layer else ("dp", "pp")
            return lax.psum(g, axes)

        grads = {
            "embed_tokens": reduce_grad(False, grads["embed_tokens"]),
            "final_norm": reduce_grad(False, grads["final_norm"]),
            "layers": {k: reduce_grad(True, v)
                       for k, v in grads["layers"].items()},
            **({"lm_head": reduce_grad(False, grads["lm_head"])}
               if lm_head is not None else {}),
        }
        return loss, grads

    param_specs = {
        "embed_tokens": repl,
        "final_norm": repl,
        "layers": {k: layer_spec for k in
                   ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "attn_norm", "mlp_norm")},
    }
    if not cfg.tie_embeddings:
        param_specs["lm_head"] = repl
    grad_specs = param_specs  # same placement as params

    sharded_lg = shard_map(
        local_loss_and_grads, mesh=mesh,
        in_specs=(param_specs, P("dp"), P("dp")),
        out_specs=(repl, grad_specs),
        check_vma=False,
    )

    def _step(state: TrainState, tokens, targets):
        loss, grads = sharded_lg(state.params, tokens, targets)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state,
                       step=state.step + 1),
            {"loss": loss, "grad_norm": optax.global_norm(grads)},
        )

    step_fn = jax.jit(_step, in_shardings=(None, batch_sh, batch_sh),
                      donate_argnums=(0,))

    def init_state() -> TrainState:
        params = jax.jit(partial(init_params, cfg),
                         out_shardings=param_sh)(jax.random.PRNGKey(seed))
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=_opt_shardings(optimizer, params, param_sh),
        )(params)
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32))

    def data_sharder(arr):
        return jax.device_put(arr, batch_sh)

    return step_fn, init_state, data_sharder
