"""Memory-compact optimizers for HBM-bound TPU training.

New work relative to the reference framework (Ray delegates optimizers to
torch; a TPU-native framework owns its optimizer memory layout — the
reference's train layer surface is train_loop_utils.py prepare_optimizer).

On a single v5e chip (15.75 GB usable HBM) a 1.1B-param model with stock
AdamW costs params 2.2 GB (bf16) + mu 2.2 GB (bf16) + nu **4.4 GB (f32)**
— the f32 second moment alone is the difference between the fast
activation-saving remat modes fitting or OOMing. ``adamw_lowmem`` stores
BOTH moments in a compact dtype (default bfloat16) while doing all update
math in f32: each step dequantizes, updates, and re-rounds, so the only
loss is storage rounding (~0.4 % relative for bf16), which second-moment
EMAs tolerate (the same trade 8-bit Adam makes much more aggressively).

Composition stays pure optax: ``scale_by_adam_compact`` is a
GradientTransformation chained with weight decay + lr, so it drops into
``make_train_step(optimizer=...)`` unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax


class ScaleByAdamCompactState(NamedTuple):
    count: chex.Array
    mu: optax.Updates
    nu: optax.Updates


def scale_by_adam_compact(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    moment_dtype: jnp.dtype = jnp.bfloat16,
) -> optax.GradientTransformation:
    """Adam scaling with BOTH moments stored in ``moment_dtype``.

    optax's ``scale_by_adam`` exposes ``mu_dtype`` but always keeps nu in
    the param dtype's width (f32 for f32/bf16 params after its internal
    promotion) — for large models nu is the single largest optimizer
    buffer. All arithmetic here runs in f32; only storage is compact.
    """

    def init_fn(params):
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=moment_dtype), params)
        nu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=moment_dtype), params)
        return ScaleByAdamCompactState(
            count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf

        def upd(g, m, v):
            g32 = g.astype(jnp.float32)
            m32 = b1 * m.astype(jnp.float32) + (1.0 - b1) * g32
            v32 = b2 * v.astype(jnp.float32) + (1.0 - b2) * g32 * g32
            step = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
            return step, m32.astype(moment_dtype), v32.astype(moment_dtype)

        flat_u, treedef = jax.tree.flatten(updates)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        out = [upd(g, m, v) for g, m, v in zip(flat_u, flat_m, flat_v)]
        steps = treedef.unflatten([o[0] for o in out])
        mu = treedef.unflatten([o[1] for o in out])
        nu = treedef.unflatten([o[2] for o in out])
        return steps, ScaleByAdamCompactState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def optimizer_state_bytes(optimizer: optax.GradientTransformation, params,
                          shardings=None) -> int:
    """Per-device bytes of optimizer state — the number ZeRO-1 divides.

    Computed from ``jax.eval_shape(optimizer.init, params)`` so no state is
    materialized. With ``shardings`` (a pytree of NamedShardings matching the
    state tree, e.g. from train/spmd's update sharding), each leaf's bytes
    are divided by its shard count, giving the HBM actually resident per
    device; without, the replicated (flat data-parallel) footprint."""
    shapes = jax.eval_shape(optimizer.init, params)
    leaves = jax.tree.leaves(shapes)
    if shardings is None:
        return sum(int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
                   for l in leaves)
    # is_leaf keeps None placeholders (unmatched leaves = replicated) so the
    # two leaf lists stay aligned.
    sh_leaves = jax.tree.leaves(shardings, is_leaf=lambda x: x is None)
    if len(sh_leaves) != len(leaves):
        raise ValueError(
            f"shardings tree has {len(sh_leaves)} leaves, optimizer state "
            f"has {len(leaves)} — a zip would silently misalign them")
    total = 0
    for leaf, sh in zip(leaves, sh_leaves):
        nbytes = int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        n_shards = 1
        if sh is not None and hasattr(sh, "spec"):
            for entry in sh.spec:
                for ax in (entry if isinstance(entry, tuple)
                           else ((entry,) if entry else ())):
                    n_shards *= sh.mesh.shape[ax]
        total += nbytes // max(n_shards, 1)
    return total


def adamw_lowmem(
    learning_rate: optax.ScalarOrSchedule = 3e-4,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    moment_dtype: jnp.dtype = jnp.bfloat16,
    mask: Optional[optax.MaskOrFn] = None,
) -> optax.GradientTransformation:
    """AdamW with compact moment storage — ~2x less optimizer HBM than
    ``optax.adamw(mu_dtype=bf16)`` (which still keeps nu in f32)."""
    tx = [scale_by_adam_compact(b1=b1, b2=b2, eps=eps,
                                moment_dtype=moment_dtype)]
    if weight_decay:
        tx.append(optax.add_decayed_weights(weight_decay, mask=mask))
    tx.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*tx)
