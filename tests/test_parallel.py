"""Mesh construction and sharding-rule tables."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import MeshSpec, build_mesh, hybrid_mesh
from ray_tpu.parallel.sharding import ShardingRules, shard_params, tree_shardings


def test_mesh_spec_sizes():
    spec = MeshSpec(dp=2, tp=4)
    assert spec.num_devices == 8
    assert spec.axis_sizes()["dp"] == 2
    assert spec.with_total(16, grow="dp").dp == 4


def test_build_mesh(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2), cpu_mesh_devices)
    assert mesh.shape["dp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.devices.size == 8


def test_mesh_too_big_raises(cpu_mesh_devices):
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(dp=100), cpu_mesh_devices)


def test_hybrid_mesh_dcn_outermost(cpu_mesh_devices):
    spec = MeshSpec(dp=2, fsdp=4, dcn_axes=("dp",))
    mesh = hybrid_mesh(spec, num_slices=2, devices_per_slice=4,
                       devices=cpu_mesh_devices)
    # each dp row (slice) must hold a contiguous run of devices
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    flat = ids.reshape(2, -1)
    for s in range(2):
        assert set(flat[s]) == set(range(s * 4, (s + 1) * 4))


def test_sharding_rules_spec():
    rules = ShardingRules()
    assert rules.spec("batch", "seq", "act_embed") == P(("dp", "fsdp"), "sp", None)
    assert rules.spec("embed", "mlp") == P(("fsdp",), "tp")
    assert rules.spec(None, "heads") == P(None, "tp")


def test_sharding_rules_no_duplicate_axis():
    rules = ShardingRules()
    # same mesh axis twice in one spec must not repeat
    s = rules.spec("mlp", "heads")  # both map to tp
    assert s == P("tp", None)


def test_rules_override():
    rules = ShardingRules().override(embed="tp")
    assert rules.spec("embed") == P("tp")


def test_shard_params_places_on_mesh(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(fsdp=2, tp=4), cpu_mesh_devices)
    params = {
        "wq": np.ones((16, 32), np.float32),
        "wo": np.ones((32, 16), np.float32),
    }
    logical = {"wq": ("embed", "heads"), "wo": ("heads", "embed")}
    sharded = shard_params(params, mesh, logical)
    assert sharded["wq"].sharding.spec == P(("fsdp",), "tp")
    # value preserved
    np.testing.assert_allclose(np.asarray(sharded["wq"]), params["wq"])


def test_tree_shardings_structure(cpu_mesh_devices):
    mesh = build_mesh(MeshSpec(dp=8), cpu_mesh_devices)
    tree = {"a": ("batch", None), "b": {"c": ("embed",)}}
    sh = tree_shardings(mesh, tree)
    assert sh["a"].spec == P(("dp", "fsdp"), None)
    assert sh["b"]["c"].spec == P("fsdp")


# ---------------------------------------------------------------------------
# pipeline parallelism (parallel/pipeline.py)
# ---------------------------------------------------------------------------

def test_pp_matches_single_device(cpu_mesh_devices):
    """pp=2 (x dp=2) pipeline loss/step must match the plain single-device
    step numerically (same init, same batch)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.pipeline import make_pp_train_step

    cfg = LlamaConfig.tiny()  # 2 layers -> 2 stages of 1
    mesh = build_mesh(MeshSpec(pp=2, dp=2), cpu_mesh_devices[:4])
    opt = optax.sgd(0.1)
    step_fn, init_state, shard = make_pp_train_step(
        cfg, mesh, num_microbatches=2, optimizer=opt, attn_impl="blockwise")
    state = init_state()

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)

    state, metrics = step_fn(state, shard(tokens), shard(targets))
    pp_loss = float(metrics["loss"])

    # Reference: plain loss on one device with identical params.
    params = init_params(cfg, jax.random.PRNGKey(0))
    ref_loss = float(loss_fn(cfg, params, jnp.asarray(tokens),
                             jnp.asarray(targets), attn_impl="blockwise",
                             remat=False, fused_ce=False))
    np.testing.assert_allclose(pp_loss, ref_loss, rtol=1e-4, atol=1e-4)

    # And training makes progress over a few steps.
    for _ in range(3):
        state, metrics = step_fn(state, shard(tokens), shard(targets))
    assert float(metrics["loss"]) < ref_loss


def test_pp_grads_match_single_device(cpu_mesh_devices):
    """One SGD step under the pipeline must produce the same loss trajectory
    as the plain step (grad correctness incl. tied-embedding psum)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.parallel.pipeline import make_pp_train_step
    from ray_tpu.train.spmd import make_llama_train_step

    cfg = LlamaConfig.tiny()
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)

    # pipeline step
    mesh_pp = build_mesh(MeshSpec(pp=2), cpu_mesh_devices[:2])
    opt = optax.sgd(0.1)
    pstep, pinit, pshard = make_pp_train_step(
        cfg, mesh_pp, num_microbatches=2, optimizer=opt,
        attn_impl="blockwise")
    pstate = pinit()
    pstate, _ = pstep(pstate, pshard(tokens), pshard(targets))
    pstate, pm = pstep(pstate, pshard(tokens), pshard(targets))

    # plain step
    mesh_1 = build_mesh(MeshSpec(dp=1), cpu_mesh_devices[:1])
    sstep, sinit, sshard = make_llama_train_step(
        cfg, mesh_1, optimizer=optax.sgd(0.1), attn_impl="blockwise",
        remat=False)
    sstate = sinit()
    sstate, _ = sstep(sstate, sshard(tokens), sshard(targets))
    sstate, sm = sstep(sstate, sshard(tokens), sshard(targets))

    # after one identical update, the second-step losses must agree
    np.testing.assert_allclose(float(pm["loss"]), float(sm["loss"]),
                               rtol=2e-3, atol=2e-3)


def test_llama_train_step_lowmem_optimizer(cpu_mesh_devices):
    """adamw_lowmem (compact-moment AdamW, train/optim.py) drops into the
    SPMD step factory: moments come back in bf16, shardings mirror params,
    and a few steps reduce the loss like stock adamw does."""
    import numpy as np
    import optax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.optim import adamw_lowmem
    from ray_tpu.train.spmd import make_llama_train_step

    cfg = LlamaConfig.tiny()
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, cfg.vocab_size, (4, 16), dtype=np.int32)
    targets = np.roll(tokens, -1, axis=1)
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2), cpu_mesh_devices[:4])

    losses = {}
    for name, opt in [("lowmem", adamw_lowmem(1e-2, weight_decay=0.1)),
                      ("adamw", optax.adamw(1e-2, weight_decay=0.1))]:
        step, init, shard = make_llama_train_step(
            cfg, mesh, optimizer=opt, attn_impl="blockwise", remat=False)
        state = init()
        tr = []
        for _ in range(6):
            state, m = step(state, shard(tokens), shard(targets))
            tr.append(float(m["loss"]))
        losses[name] = tr
        if name == "lowmem":
            import jax
            import jax.numpy as jnp

            mu_leaf = jax.tree.leaves(state.opt_state[0].mu)[0]
            nu_leaf = jax.tree.leaves(state.opt_state[0].nu)[0]
            assert mu_leaf.dtype == jnp.bfloat16
            assert nu_leaf.dtype == jnp.bfloat16
    assert losses["lowmem"][-1] < losses["lowmem"][0]
    # Tracks stock adamw closely over a short horizon.
    assert abs(losses["lowmem"][-1] - losses["adamw"][-1]) < 0.35
