"""Typed, env-overridable runtime configuration flags.

Same capability as the reference's RAY_CONFIG X-macro table
(reference: src/ray/common/ray_config_def.h — 233 flags, overridable via
``RAY_<name>`` env vars or a system-config JSON): a single registry of typed
flags with defaults, overridable per-process via ``RTPU_<NAME>`` environment
variables or a dict passed to ``Config.load(overrides=...)``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "RTPU_"


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


@dataclass
class Config:
    """Runtime flags. Add new flags as dataclass fields; env var = RTPU_<UPPER_NAME>."""

    # --- scheduling (reference: raylet scheduling policy knobs) ---
    scheduler_spread_threshold: float = 0.5  # hybrid policy: local-first until this load
    worker_lease_timeout_s: float = 30.0
    # Actor placement: how long a fresh worker fork may take to register
    # before the placement fails. Worker boot imports the framework (and
    # often jax) — seconds of CPU each; concurrent forks on small hosts
    # serialize, so this must be generous (reference: worker startup is
    # bounded by worker_register_timeout_seconds).
    worker_start_timeout_s: float = 120.0
    max_workers_per_node: int = 64
    worker_idle_ttl_s: float = 60.0  # idle pooled workers are reaped after this
    worker_startup_concurrency: int = 8
    lease_keepalive_s: float = 2.0  # idle driver-cached leases returned after this
    lease_spill_check_s: float = 0.3  # queued lease looks for a freer node after this
    # Max worker leases granted by ONE lease_workers RPC (the submitter
    # sizes requests by queue depth; the daemon grants up to this many idle
    # workers per round trip instead of one per RPC).
    lease_batch_max: int = 16
    # Idle workers the daemon keeps prestarted AHEAD of demand once leases
    # are being requested (0 disables): fan-out bursts land on a warm pool
    # instead of serializing on fork+register (~1 s of CPU per worker).
    idle_worker_pool: int = 1

    # --- object store (reference: plasma + spilling thresholds, ray_config_def.h:680-697) ---
    object_store_memory_bytes: int = 2 * 1024**3
    object_spilling_threshold: float = 0.8
    min_spilling_size_bytes: int = 100 * 1024**2
    max_fused_object_count: int = 2000
    inline_object_max_bytes: int = 100 * 1024  # small results ride in RPC replies

    # --- object transfer plane (reference: object_manager chunked transfer
    # knobs, ray_config_def.h object_manager_default_chunk_size) ---
    # Range size for chunked/pipelined pulls: each pull is split into
    # fixed-size ranges fetched concurrently from multiple serving copies,
    # and the cut-through watermark advances in units of this chunk.
    transfer_chunk_bytes: int = 16 * 1024 * 1024
    # Requests pipelined per transfer connection (the server streams range
    # after range without a request/response latency gap).
    transfer_pipeline_depth: int = 4
    # Serving copies the owner hands one puller (pipelined multi-source
    # pulls split ranges across them).
    transfer_max_sources: int = 3
    # Same-host zero-copy reads: a puller whose host boot id matches the
    # holder node's maps that node's arena directly and serves get() from
    # a pinned view — no wire transfer (plasma-style same-host sharing).
    # Disable to force every cross-node pull onto the TCP range engine
    # (e.g. when benchmarking the transfer plane itself).
    transfer_same_host_arena: bool = True

    # --- compiled graphs (ray_tpu/dag) ---
    # Channel transport for compiled DAGs in cluster mode: "direct" moves
    # payloads peer-to-peer over the actor push-frame path (head KV touched
    # once at compile time for route exchange, never per step); "kv" is the
    # head-KV fallback channel (every hop costs kv_put/kv_get head RPCs).
    # Local mode always uses in-process queues regardless of this knob.
    dag_channel: str = "direct"
    # Bounded execute_async() window: executions admitted into the pipeline
    # before the oldest completes (pipeline fill depth; backpressure blocks
    # the submitter beyond it).
    dag_max_inflight: int = 8
    # Per-channel capacity in unacked in-flight values: a direct-channel
    # writer blocks once this many writes are unacknowledged by a reader
    # (per-hop backpressure); also the queue bound of local channels.
    dag_channel_capacity: int = 16
    # Direct-channel payloads at or under this many serialized bytes ride
    # inline in the push frame; larger ones (activations/grads) become
    # store-backed buffers — same-host readers map them as pinned arena
    # views, cross-host readers pull them over the transfer plane.
    dag_inline_max_bytes: int = 64 * 1024

    # --- control plane ---
    health_check_period_s: float = 1.0
    # Failure-detection fast path (sub-minute recovery): how often the node
    # daemon polls its worker processes for death. The reap loop's idle-TTL
    # cadence (worker_idle_ttl_s/4 = 15 s) is far too slow to notice a
    # SIGKILLed train worker; this dedicated waitpid(WNOHANG) sweep costs
    # microseconds and bounds worker-death detection at ~this interval.
    # <= 0 falls back to reap-loop-only detection.
    worker_death_poll_s: float = 0.25
    # When a node daemon's persistent head connection drops, the head waits
    # this long for a re-register/heartbeat and then declares the node dead
    # immediately — instead of waiting for heartbeat aging (up to
    # health_check_period_s * health_check_failure_threshold = 5 s). A dead
    # daemon process closes its sockets at once, so this catches real node
    # death fast while the grace absorbs reconnect blips. < 0 disables the
    # fast path (heartbeat aging only).
    node_disconnect_grace_s: float = 0.5
    # Superseded by telemetry_flush_interval_s (the batched telemetry push
    # carries the task events); kept so existing RTPU_TASK_EVENT_* env
    # settings don't error, but no longer read.
    task_event_flush_interval_s: float = 0.5
    health_check_timeout_s: float = 5.0
    health_check_failure_threshold: int = 5
    gcs_pubsub_poll_timeout_s: float = 30.0
    actor_max_restarts_default: int = 0

    # --- core worker ---
    task_retry_delay_s: float = 0.1
    max_lineage_bytes: int = 64 * 1024**2
    max_direct_call_object_size: int = 100 * 1024
    task_events_buffer_size: int = 10000
    # Worker-side cache of deserialized function/class definitions fetched
    # from the head registry (LRU by serialized size; see core/fn_registry).
    fn_cache_max_bytes: int = 64 * 1024**2

    # --- memory monitor (reference: _private/memory_monitor.py:97 +
    # raylet/worker_killing_policy_group_by_owner.cc) ---
    memory_monitor_interval_s: float = 0.5  # 0 disables the watcher
    memory_usage_threshold: float = 0.95
    # Optional worker-memory budget: when set, the watcher also kills when
    # the sum of worker RSS exceeds threshold*budget (node-level pressure
    # against the detected cgroup/MemTotal limit always applies).
    memory_limit_bytes: int = 0

    # Head WAL group commit: mutation records buffered this long before one
    # coalesced write+flush. 0 = same-event-loop-tick coalescing (burst
    # mutations share one write, nothing outlives the tick that logged it);
    # > 0 trades a bounded durability window for fewer writes under churn.
    wal_group_commit_ms: float = 0.0

    # --- head fault tolerance (crash-consistent control plane) ---
    # Total wall budget one retrying head RPC (RpcClient.call_retrying)
    # may spend riding out a head crash/restart/partition before the
    # failure surfaces. This is what keeps RpcConnectionLost from
    # propagating into drivers, the serve controller, and the train
    # controller during a head outage shorter than the budget; mutations
    # stay exactly-once across the retries via the req-id dedup table.
    head_retry_budget_s: float = 30.0
    # Retry backoff bounds: each attempt sleeps uniform in [0, cap) with
    # cap doubling from base to max (full jitter — a restarted head with
    # hundreds of clients must see staggered retries, not a stampede).
    head_retry_base_s: float = 0.05
    head_retry_max_s: float = 2.0
    # Completed mutation request ids the head remembers (WAL-logged and
    # snapshotted with the tables they guard) so a retry after
    # crash-before-ACK is answered from the record instead of re-applied.
    # Oldest evicted beyond the bound; a retry older than the eviction
    # horizon falls back to the per-RPC natural-idempotence checks.
    head_dedup_max: int = 4096
    # Daemon heartbeat RPC timeout: bounds how long a partition-dropped
    # heartbeat frame can stall the loop before the daemon treats the
    # head as unreachable and enters its reconnect path. <= 0 disables
    # the bound (pre-FT behavior: a dropped frame wedges the loop).
    daemon_heartbeat_timeout_s: float = 5.0

    # --- fleet scale (thousand-node head fast path) ---
    # Delta heartbeats (ray_syncer's design, extending the PR-9 sid-table
    # telemetry scheme to the resource plane): after a full sync at
    # registration, a daemon ships only CHANGED availability keys per
    # heartbeat — or an empty beat when nothing moved — instead of its full
    # available/resources/demands maps every period. The head replies
    # ``resync`` (and daemons fall back to full maps) whenever it lacks a
    # baseline; head restarts resync through the existing re-register
    # path. 0 restores full-map heartbeats (the scale bench's "before").
    delta_heartbeat_enabled: bool = True
    # Indexed scheduling state: _pick_node walks a lazily-maintained
    # max-heap over effective CPU (plus a label inverted index and O(1)
    # affinity lookup) and _assign_bundles reads cached free-sums with
    # lazy per-node copies, instead of linearly scanning + deep-copying
    # the whole node table per placement/lease. 0 restores the linear
    # scans (kept as the parity reference in tests/test_scale.py).
    indexed_scheduler_enabled: bool = True
    # Pubsub fan-out coalescing window: publishes buffered this long are
    # batched into ONE pub_batch frame per subscriber connection, sent
    # concurrently — instead of one awaited notify per subscriber per
    # event. <= 0 restores immediate per-event, per-subscriber sends.
    pubsub_batch_window_s: float = 0.005
    # Head self-metrics cadence: the event-loop lag gauge
    # (head_loop_lag_s) and the per-RPC-method rate/latency series riding
    # the rpc.counts table are sampled this often into the watchdog store
    # and surfaced by head_status / `ray_tpu status`. <= 0 disables.
    head_metrics_period_s: float = 0.5
    # Simulated fleet (core/cluster/sim_fleet.py): default node count the
    # harness stands up when none is given, and the fake TPU inventory
    # each simulated node registers ("<kind>-<chips>", e.g. "v5e-8" →
    # resources {CPU, TPU: 8} + accelerator/topology labels).
    sim_fleet_nodes: int = 100
    sim_fleet_geometry: str = "v5e-8"
    # Streaming-split ingest backpressure: per-consumer prefetch bound —
    # blocks a SplitCoordinator may queue ahead of each consumer before
    # its producer thread stalls. Stalls/drains are counted in the
    # federated ``data_split_stall`` / ``data_split_empty_poll`` metrics
    # so the scale bench's ingest phase measures throughput instead of
    # unbounded buffering.
    data_split_prefetch_blocks: int = 8

    # --- collectives / multi-slice training ---
    # Cross-slice (DCN) wire format for hierarchical allreduce in multi-slice
    # collective groups ("none" | "bf16" | "int8"). "none" keeps the input
    # dtype. "bf16" halves DCN bytes at ~1e-3 relative error. "int8" is the
    # EQuARX-style per-bucket-scaled format: ~4x fewer DCN bytes at ~4e-3
    # relative error on the summed gradient (see tests/test_collective.py
    # parity tolerances). Per-group override: init_collective_group(
    # dcn_quant=...).
    collective_dcn_quant: str = "none"
    # Elements sharing one f32 scale in the int8 DCN format. Smaller buckets
    # track outliers better (lower error, more scale overhead); 256 keeps
    # scale overhead at 1.6% of payload.
    collective_dcn_quant_bucket: int = 256

    # --- kernels / train-step autotuning (env-only knobs) ---
    # The Pallas/loss kernel tuning knobs are read DIRECTLY from the
    # environment at trace time rather than through this Config: the ops
    # modules must stay importable without runtime initialization, and the
    # autotuner (ray_tpu/autotune) flips them per candidate between
    # compiles (Candidate.applied_env). Documented here because this file
    # is the flag registry of record:
    #   RTPU_FLASH_BLOCK_Q / RTPU_FLASH_BLOCK_K (512): flash-attention
    #     kernel block sizes — fwd, fused + split backward, ring chunk
    #     kernels; must divide the sequence length.
    #   RTPU_CE_CHUNK (512): fused cross-entropy sequence-chunk size —
    #     fewer scan steps vs a bigger [B, chunk, V] logits workspace.
    #   RTPU_FLASH_FUSED_BWD (1): fused dq+dkv backward kernel; 0 = the
    #     split dq / dkv kernel pair. Read ONCE at ops/attention import
    #     (module-level FUSED_BWD) — set it before the process starts;
    #     not flippable per candidate, unlike the trace-time knobs above.
    #   RTPU_FLASH_VMEM_LIMIT_MB (by TPU generation): scoped-VMEM ceiling
    #     for the flash kernels; 0 forces the compiler default.
    #   RTPU_HBM_BUDGET_GB (detected from the backend): HBM budget the
    #     autotuner's pruning tier compares predictions against.
    #   RTPU_AUTOTUNE_CACHE (<repo>/AUTOTUNE_CACHE.json): measured-
    #     throughput cache path (keyed device kind + geometry + config).
    #   RTPU_BENCH_MAX_MEASURE (6): candidates measured per bench round.

    # --- train ---
    # Compute the grad-norm metric every N steps (1 = every step, the
    # old behavior). The global-norm reduction costs ~1.6% of a Llama-1B
    # step (PERF_STEP.json r05: 7.8 ms of 505); skipped steps report
    # grad_norm = -1. Default for make_train_step(grad_norm_every=None).
    train_grad_norm_every: int = 1
    # Set latency-hiding-scheduler / async-collective LIBTPU flags on train
    # workers before backend init, so DCN collectives overlap the next
    # microbatch's compute (train/backend.py _XLA_PERF_FLAGS). Flags ride
    # LIBTPU_INIT_ARGS, so they are inert on CPU hosts. Extra flags can be
    # appended via RTPU_TRAIN_XLA_PERF_FLAGS_EXTRA (space-separated).
    train_xla_perf_flags: bool = True

    # --- serve request resilience (per-deployment, not env flags) ---
    # The serve data-plane resilience knobs are deployment-scoped and live
    # on DeploymentConfig (ray_tpu/serve/config.py), set per deployment via
    # @serve.deployment(...) — different models need different budgets, so
    # a process-wide flag would be wrong. Documented here because this file
    # is the flag registry of record:
    #   request_timeout_s (30): default per-request budget; the absolute
    #     deadline rides handle → router → replica → batcher, bounding
    #     queue waits and dropping expired requests before they spend TPU
    #     time. Per call: handle.options(timeout_s=...); per HTTP request:
    #     x-request-timeout-s header; gRPC uses the client's deadline.
    #   max_queued_requests (256): router admission control — callers
    #     parked beyond this are shed with Overloaded (HTTP 503 +
    #     Retry-After / gRPC RESOURCE_EXHAUSTED). -1 = unbounded.
    #   replica_queue_slack (8): replica-side admission — reject once
    #     ongoing > max_ongoing_requests + slack (N routers can each fill
    #     their own per-router cap against one replica).
    #   retry_policy (RetryPolicy): max_retries (1) assignment retries on
    #     replica death / replica-side sheds, excluding replicas already
    #     tried; retry_never_sent (True) single safe retry of calls that
    #     provably never reached a replica; hedge_after_s (None) tail
    #     hedging for idempotent calls; backoff_s (0) jittered backoff.
    #   circuit_breaker (CircuitBreakerConfig): failure_threshold (3)
    #     consecutive failures → open; open_s (2.0) cooldown;
    #     half_open_probes (1) trial requests; latency_factor (5.0) /
    #     latency_min_samples (16) latency-outlier trip vs fleet median.

    # --- serve inference fast path (KV-block-aware prefix routing +
    #     disaggregated P/D KV hand-off; serve/prefix.py, serve/router.py,
    #     llm/pd.py) ---
    # How often the controller polls each replica's router_meta() for its
    # prefix-cache block hashes and piggybacks them on the long-poll
    # replica snapshot. Replicas that answer None (non-LLM deployments)
    # are probed once and never polled again. <= 0 disables publication.
    serve_prefix_publish_period_s: float = 0.5
    # Router-side prefix-map entry TTL: an entry not refreshed by a
    # snapshot within this window is ignored (ages out state from a dead
    # controller / wedged long-poll; dead and draining replicas are
    # dropped from the map immediately on every snapshot). Aged-out
    # entries degrade to pow-2 routing — locality lost, correctness kept.
    serve_prefix_map_ttl_s: float = 30.0
    # Deployment/engine-scoped knobs documented here for the registry of
    # record (set on LLMConfig, not env flags):
    #   prefix_block_tokens (32): token-block granularity of the chain
    #     hashes replicas publish and request hints are computed with.
    #   pd_transfer_mode ("store"): disaggregated prefill→decode KV
    #     hand-off transport — "store" ships ObjectRefs to store-backed
    #     ndarrays over the zero-copy object plane (no serialize on the
    #     TTFT path); "inline" pickles the KV through the handle call.

    # --- chaos (ray_tpu/chaos) ---
    # Master gate for the fault-injection layer. Rules come from the
    # RTPU_CHAOS env var (JSON list), RTPU_CHAOS_FILE, the `chaos` CLI verb,
    # or util.state.inject_chaos(); with this False every installed rule is
    # inert (a production cluster can carry a chaos schedule disarmed).
    # Rule schema of record: ray_tpu/chaos/injector.py. Head-outage drills
    # use two dedicated points: ``head.tick`` (action "kill" = abrupt
    # control-plane death, no final flush — restart must replay the WAL)
    # and ``partition`` (directional head⇄node frame drop/delay; rule keys
    # ``match={"node": <regex>}`` and ``direction`` in
    # "to_head" | "from_head" | "both"). CLI: `ray_tpu chaos kill-head` /
    # `ray_tpu chaos partition --node <regex> [--direction D] [--drop]`.
    chaos_enabled: bool = True

    # --- train recovery ---
    # In-cluster replica shards a ReplicaStore keeps per run (newest
    # complete sets win; older steps are pruned). 2 lets a restore proceed
    # even when a worker died mid-way through pushing step N.
    train_replica_keep: int = 2
    # Seconds session.replicate()'s background pusher waits for one shard
    # push before counting it failed; replication disables itself after 3
    # consecutive failures (it must never become the thing that stalls or
    # kills a healthy run).
    train_replica_push_timeout_s: float = 30.0

    # --- observability ---
    # Flight recorder: JSON debug bundles dumped on task failure / worker
    # death / actor death under <temp_dir>/flight_records.
    flight_recorder_enabled: bool = True
    flight_recorder_max_bundles: int = 40
    # Cluster telemetry: how often each process pushes its metric snapshot,
    # finished spans, and drained task events to the head (<= 0 disables
    # the push entirely).
    telemetry_flush_interval_s: float = 0.5

    # --- request tracing (ray_tpu/util/tracing.py) ---
    # Head-sampling rate for serve ingress requests: the DeploymentHandle
    # draws one verdict per request and every downstream span (router,
    # replica, batcher, engine, DAG/KV hops) inherits it. Per-deployment
    # override: @serve.deployment(trace_sample_rate=...) rides the same
    # ResilienceSettings snapshot the other data-plane knobs use. Only
    # meaningful once tracing.enable_tracing() turned the master gate on.
    trace_sample_rate: float = 0.01
    # Tail-sampling ring bounds: spans of UNsampled traces are ringed per
    # trace_id (promotable by a retroactive keep when the request ends
    # slow / shed / expired / errored / breaker-implicated) instead of
    # discarded. Distinct traces held, spans kept per trace, and the ring
    # TTL — all per process; past any bound the oldest die unkept.
    trace_tail_traces: int = 512
    trace_tail_spans_per_trace: int = 64
    trace_tail_ttl_s: float = 30.0
    # "Ended slow" keep verdict: rolling per-deployment latency window —
    # sample count and the minimum history before the p99 gate judges
    # (no verdicts off a cold window).
    trace_slow_window: int = 512
    trace_slow_min_samples: int = 64
    # Recent exemplar (trace_id, value) pairs each histogram SERIES keeps
    # so TTFT/TPOT/latency buckets link back to traces (/api/metrics,
    # /api/traces, watchdog incident bundles). 0 disables exemplars.
    metrics_exemplar_count: int = 4

    # --- health watchdog (ray_tpu/observability) ---
    # Master gate: with this on, every process's telemetry flusher derives
    # delta-encoded samples for the hot-path series (train step/tokens/MFU,
    # collective latency+bytes, serve TTFT/TPOT/queue/shed, transfer bytes,
    # per-process RSS/HBM) and the head runs streaming anomaly detectors
    # over them, auto-capturing evidence on a trip. Off = no sampling, no
    # detection, no auto-captures (the pull-based surfaces still work).
    watchdog_enabled: bool = True
    # Head loop cadence: heartbeat-gap sampling + incident assembly tick.
    # Detection itself is streaming (evaluated at sample arrival), so this
    # bounds evidence-capture latency, not detection latency.
    watchdog_eval_interval_s: float = 0.5
    # Rolling points kept per series (ring buffer) and distinct series the
    # store accepts before dropping (watchdog_dropped_samples counts).
    watchdog_series_samples: int = 360
    watchdog_series_max: int = 4096
    # Detector firing discipline (see observability/detectors.py): no
    # verdicts before `warmup` samples; `debounce` CONSECUTIVE breaching
    # samples to trip; a tripped series is muted for `cooldown_s`.
    watchdog_warmup_samples: int = 10
    watchdog_debounce: int = 3
    watchdog_cooldown_s: float = 30.0
    # Spike rules (step-time drift, collective latency, serve p99,
    # heartbeat jitter): robust z-score above this AND value above
    # ratio * baseline (both, so steady-but-noisy series can't trip).
    watchdog_z_threshold: float = 6.0
    watchdog_spike_ratio: float = 2.0
    # Absolute floors for the baseline-free rules: shed/expiry rate
    # (healthy = 0/s), router queue growth (levels are fine, sustained
    # growth is the death spiral), per-process RSS/HBM leak slope.
    watchdog_shed_rate_per_s: float = 0.5
    watchdog_queue_growth_per_s: float = 2.0
    watchdog_mem_slope_mb_s: float = 256.0
    # Incident retention (bounded deque on the head).
    watchdog_max_incidents: int = 64
    # Anomaly-triggered targeted profiler captures (PR-5 profile_node RPC,
    # scoped to the implicated node) — hard guardrails: concurrent-capture
    # cap, per-node cooldown, and a lifetime budget per head, so the
    # watchdog can never pile profiling onto an already-sick cluster.
    watchdog_auto_capture: bool = True
    watchdog_capture_seconds: float = 1.5
    watchdog_max_auto_captures: int = 1
    watchdog_capture_cooldown_s: float = 60.0
    watchdog_capture_budget: int = 20

    # --- goodput ledger (ray_tpu/observability/goodput.py) ---
    # Master gate: with this on, every live TrainContext carries a
    # RankLedger classifying its wall clock into the goodput phase
    # taxonomy (snapshots ride the existing train-stats telemetry rows),
    # controllers/heads stamp restart/outage events onto the same pushes,
    # and the head aggregates a per-run + fleet goodput rollup. Off = no
    # ledgers, no event legs, no head store.
    goodput_enabled: bool = True
    # Badput-over-threshold watchdog rule: a run burning more than this
    # percentage of its chip-seconds in ONE badput phase opens a
    # `badput_over_threshold` incident with the ledger window attached.
    goodput_badput_pct: float = 50.0
    # No incident before the run has attributed at least this much wall
    # time (init/compile dominate any run's first seconds by design).
    goodput_badput_min_wall_s: float = 10.0
    # Per-run cooldown between badput incidents.
    goodput_badput_cooldown_s: float = 60.0
    # Head-side rollup/gauge/incident-check cadence (piggybacked on
    # telemetry ingest, throttled to at most once per this interval).
    goodput_check_interval_s: float = 5.0

    # --- on-demand profiler (ray_tpu/profiling) ---
    # Python stack-sampler rate for `profile` captures. 100 Hz keeps the
    # measured overhead within the <=2% budget PERF_PROFILER.json tracks;
    # raise for finer flamegraphs on beefy hosts. The sampler clamps any
    # requested rate to 1 kHz — above that the per-sample GIL cost
    # approaches the interval and a single profile request would busy-loop
    # every process in the cluster.
    profiler_sample_hz: float = 100.0
    # Hard ceiling on one capture's duration: a fat-fingered
    # `profile --seconds 86400` must not leave samplers running for a day.
    # Requests are clamped, not rejected.
    profiler_max_capture_s: float = 60.0
    # Concurrent `profile_node` captures a node daemon will run at once;
    # excess requests are refused (and counted in
    # profiler_dropped_captures) so profiling can't pile onto a node that
    # is already being profiled.
    profiler_max_concurrent_captures: int = 2
    # Allow `jax.profiler` device-trace capture inside profile sessions.
    # Off, or on a process without an initialized non-CPU jax backend, the
    # capture carries a no-op marker instead of a trace.
    profiler_xla_trace: bool = True

    # --- env-only knobs and internal plumbing (registry of record) ---
    # These are read straight from the environment (no Config field): the
    # first group is user-settable, the second is wiring the node daemon
    # stamps into forked worker processes (set them yourself only in
    # tests). rtlint rule R5 enforces that every RTPU_* read in the tree
    # has an entry here or a Config field.
    #   RTPU_USAGE_STATS_ENABLED (1): usage-stats collection master
    #     switch (usage/__init__.py); "0" disables.
    #   RTPU_PEAK_FLOPS (backend-detected): per-device peak FLOP/s
    #     override for the MFU/goodput denominators; without it the
    #     generation table in accelerators/flops.py resolves from the
    #     initialized backend's device_kind.
    #   RTPU_CONTAINER_RUNNER ("podman"): container runtime binary for
    #     runtime_env containers; tests point it at a stub
    #     (runtime_env/container.py).
    #   RTPU_JAX_PLATFORMS (unset): forces jax.config platforms in worker
    #     processes BEFORE backend init (worker_main.py) — the dryrun
    #     uses it to pin forked workers to cpu.
    #   RTPU_HEAD / RTPU_NODE_DAEMON (internal): head / daemon host:port
    #     a forked worker connects back to.
    #   RTPU_NODE_ID (internal): hex node id of the owning daemon,
    #     stamped into worker registration.
    #   RTPU_WORKER_NONCE (internal): fork nonce tying a worker
    #     registration to the lease that requested it.
    #   RTPU_PARENT_PID (internal): daemon pid a worker watches so
    #     orphaned workers exit when the daemon dies.
    #   RTPU_SHM_NAME (internal): shared-memory arena name workers map
    #     for the same-host zero-copy object plane.

    # --- RL vectorized Podracer paths (registry of record) ---
    # The vectorized-RL knobs live on rl/ppo.py's PPOConfig rather than
    # here (they are per-algorithm, not per-process), but this block is
    # their registry of record for rtlint R5 and discoverability:
    #   PPOConfig.vectorized (False): route JAX-implemented envs
    #     (rl/vec_env.py registry) to the fused Anakin program
    #     (num_env_runners == 0) or Sebulba streaming actors
    #     (num_env_runners > 0); Python-only envs keep the EnvRunner path.
    #   PPOConfig.num_envs (0): total vectorized envs; 0 derives
    #     num_envs_per_runner x max(1, num_env_runners).
    #   PPOConfig.unroll_len (0): scan unroll length per rollout block;
    #     0 falls back to rollout_len.
    #   PPOConfig.sebulba_staleness (2): learner drops trajectory blocks
    #     older than this many weight versions (consume-time check).
    #   RTPU_RL_NUM_ENVS / RTPU_RL_UNROLL_LEN / RTPU_RL_ANAKIN_DEVICES
    #     (bench-only): geometry overrides read by devbench/rl_bench.py,
    #     not by the library (Anakin itself takes the device count via
    #     PPOConfig.extra["anakin_devices"]).

    # --- tpu ---
    tpu_visible_chips_env: str = "TPU_VISIBLE_CHIPS"
    tpu_premapped_buffer_bytes: int = 0  # 0 = library default

    # --- misc ---
    temp_dir: str = field(default_factory=lambda: os.environ.get("RTPU_TEMP_DIR", "/tmp/ray_tpu"))
    log_level: str = "INFO"

    @classmethod
    def load(cls, overrides: dict[str, Any] | None = None) -> "Config":
        cfg = cls()
        for f in fields(cls):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key in os.environ:
                typ = type(getattr(cfg, f.name))
                setattr(cfg, f.name, _coerce(os.environ[env_key], typ))
        for k, v in (overrides or {}).items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown config flag: {k}")
            setattr(cfg, k, v)
        return cfg

    @classmethod
    def from_json(cls, payload: str) -> "Config":
        return cls.load(json.loads(payload))

    def to_dict(self) -> dict[str, Any]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.load()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
