"""R5 fixture: the PR-7 undocumented-env-knob class.

The PR-7 satellite hand-found two RTPU_* env reads with no registry
entry in utils/config.py (RTPU_FLASH_FUSED_BWD, RTPU_FLASH_VMEM_LIMIT_MB)
— knobs nobody could discover without grepping the tree. Every RTPU_*
read must resolve to a Config field or a documented env-only entry."""

import os


def flash_block_q() -> int:
    # BUG (PR-7): env knob with no registry entry anywhere.
    return int(os.environ.get("RTPU_FIXTURE_SECRET_KNOB", "512"))


def vmem_limit() -> int:
    # BUG: subscript read of an unregistered knob.
    return int(os.environ["RTPU_FIXTURE_OTHER_KNOB"])
