"""Streaming executor (reference capability:
python/ray/data/_internal/execution/streaming_executor.py:77 — pull-based
streaming over blocks-as-refs with in-flight budgets and backpressure).

The plan is a linear chain of stages. Each map stage keeps a bounded pool of
in-flight remote tasks; completed blocks flow downstream without waiting for
the stage to finish. AllToAll stages are barriers that run their own
distributed shuffle. The whole loop is a generator: consumers pull
(block_ref, meta) pairs, which is itself the final backpressure.
"""

from __future__ import annotations

import collections
from typing import Any, Iterator

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.datasource import ReadTask
from ray_tpu.data.plan import AllToAll, FusedMapStage, InputData, LimitOp, Read

_exec_metrics_cache: dict | None = None


def _exec_metrics() -> dict:
    """Lazy federated counters for streaming-executor backpressure — created
    once per process (re-instantiating a same-named Counter would re-register
    and orphan the prior series)."""
    global _exec_metrics_cache
    if _exec_metrics_cache is None:
        from ray_tpu.util.metrics import Counter

        _exec_metrics_cache = {
            "backpressure": Counter(
                "data_stage_backpressure",
                "streaming stage launches blocked by the output-buffer budget",
                ("stage",)),
        }
    return _exec_metrics_cache


def _run_block_fn(block_fn, block: Block):
    out = block_fn(block)
    acc = BlockAccessor(out)
    return out, {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


def _run_read_task(task: ReadTask):
    out = task()
    acc = BlockAccessor(out)
    return out, {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


def _slice_block(block: Block, start: int, end: int):
    out = BlockAccessor(block).slice(start, end)
    return out, {"num_rows": end - start}


class ActorPoolStrategy:
    """compute= argument for map_batches (reference capability:
    ray.data.ActorPoolStrategy — autoscaling actor-pool map operator for
    stateful or accelerator-bound transforms). ``min_size``/``max_size``
    make the pool elastic: it grows while the stage's input queue outruns
    the actors and shrinks back when they idle (reference:
    _internal/execution/operators/actor_pool_map_operator.py)."""

    def __init__(self, size: int | None = None, *, min_size: int | None = None,
                 max_size: int | None = None, num_cpus: float = 1.0,
                 num_tpus: float = 0.0, resources: dict | None = None):
        if size is None and min_size is None and max_size is None:
            size = 2
        self.min_size = int(min_size if min_size is not None
                            else (size if size is not None else 1))
        self.max_size = int(max_size if max_size is not None
                            else (size if size is not None
                                  else self.min_size))
        if self.min_size < 1 or self.max_size < self.min_size:
            raise ValueError(
                f"invalid pool bounds [{self.min_size}, {self.max_size}]")
        self.size = self.min_size  # initial size (back-compat attribute)
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.resources = resources or {}


class _MapWorker:
    """Actor applying a fused block fn; holds user state (e.g. a compiled
    model) across blocks."""

    def __init__(self, block_fn):
        self._fn = block_fn

    def apply(self, block: Block):
        return _run_block_fn(self._fn, block)

    def ping(self):
        return True


class _StageExec:
    """Runtime state of one map stage."""

    # Wall-clock seconds of continuous idleness before an elastic pool
    # retires one actor above min_size (ticks would shrink a warm pool
    # sitting behind a slow upstream stage in milliseconds).
    POOL_IDLE_S = 10.0

    def __init__(self, stage: FusedMapStage, ctx: DataContext, api,
                 n_stages: int = 1):
        self.stage = stage
        self.ctx = ctx
        self.api = api
        # Per-stage byte budget measured against the node's object-store
        # arena (reference: ResourceManager op budgets against
        # object_store_memory): the stages of a pipeline collectively get
        # object_store_budget_fraction of the arena.
        try:
            from ray_tpu.utils.config import get_config

            arena = get_config().object_store_memory_bytes
        except Exception:
            arena = 0
        self.byte_budget = ctx.max_output_bytes_buffered
        if arena:
            share = int(arena * ctx.object_store_budget_fraction
                        / max(1, n_stages))
            self.byte_budget = min(self.byte_budget, max(share, 1 << 20))
        self.input_queue: collections.deque = collections.deque()
        self.upstream_done = False
        # Backpressure accounting: one stall per transition into the
        # budget-blocked state (input waiting but output buffers full), not
        # one per scheduler tick — the federated counter then reads as
        # "how often did this stage hit its budget", not loop frequency.
        self.backpressure_stalls = 0
        self._bp_blocked = False
        try:
            self._metrics = _exec_metrics()
        except Exception:
            self._metrics = None
        # meta_ref -> (block_ref, actor_index|None, seq)
        self.in_flight: dict = {}
        self.outputs: collections.deque = collections.deque()
        # Deterministic block order (reference: ray.data preserves block
        # order end-to-end): tasks complete in any order, but outputs are
        # released strictly in input order.
        self._seq_in = 0
        self._seq_out = 0
        self._pending_out: dict[int, tuple] = {}
        self._remote_fn = api.remote(num_cpus=ctx.task_num_cpus, num_returns=2)(
            _run_block_fn
        )
        self._pool = None
        self._pool_load: list[int] = []
        self._pool_idle_since: float | None = None
        self._actor_cls = None
        self._fn_ref = None
        if isinstance(stage.compute, ActorPoolStrategy):
            comp = stage.compute
            self._actor_cls = api.remote(
                num_cpus=comp.num_cpus, num_tpus=comp.num_tpus,
                resources=comp.resources,
            )(_MapWorker)
            self._fn_ref = api.put(stage.block_fn)
            self._pool = [self._actor_cls.remote(self._fn_ref)
                          for _ in range(comp.min_size)]
            self._pool_load = [0] * comp.min_size

    def _autoscale_pool(self) -> None:
        """Elastic pool sizing: grow while the queue outruns the actors
        AND the stage can actually launch (a stage throttled by its output
        byte budget must not ramp actors that can do no work), capped by
        the in-flight task limit; retire an idle actor after a quiet
        wall-clock spell (down to min_size)."""
        import time as _time

        comp = self.stage.compute
        if self._pool is None or comp.min_size == comp.max_size:
            return
        cap = min(comp.max_size, self.ctx.max_tasks_in_flight_per_stage)
        if (len(self.input_queue) > 2 * len(self._pool)
                and len(self._pool) < cap and self.can_launch()):
            self._pool.append(self._actor_cls.remote(self._fn_ref))
            self._pool_load.append(0)
            self._pool_idle_since = None
            return
        busy = len(self.input_queue) + sum(self._pool_load)
        if busy == 0 and len(self._pool) > comp.min_size:
            now = _time.monotonic()
            if self._pool_idle_since is None:
                self._pool_idle_since = now
            elif now - self._pool_idle_since >= self.POOL_IDLE_S:
                self._pool_idle_since = now
                actor = self._pool.pop()  # retire the newest
                self._pool_load.pop()
                try:
                    self.api.kill(actor)
                except Exception:
                    pass
        else:
            self._pool_idle_since = None

    @property
    def done(self) -> bool:
        return (self.upstream_done and not self.input_queue
                and not self.in_flight and not self.outputs)

    def can_launch(self) -> bool:
        if not self.input_queue:
            return False
        if len(self.in_flight) >= self.ctx.max_tasks_in_flight_per_stage:
            return False
        # _pending_out holds completed blocks awaiting earlier sequence
        # numbers — they're buffered memory too, or the ordering buffer
        # would bypass the budgets entirely.
        n_buffered = len(self.outputs) + len(self._pending_out)
        if n_buffered >= self.ctx.max_output_blocks_buffered:
            self._note_backpressure()
            return False
        buffered = sum(m.get("size_bytes", 0) for _, m in self.outputs)
        buffered += sum(m.get("size_bytes", 0)
                        for _, m in self._pending_out.values())
        if buffered >= self.byte_budget:
            self._note_backpressure()
            return False  # byte budget (reference: ResourceManager)
        self._bp_blocked = False
        return True

    def _note_backpressure(self) -> None:
        if self._bp_blocked:
            return
        self._bp_blocked = True
        self.backpressure_stalls += 1
        if self._metrics is not None:
            self._metrics["backpressure"].inc(
                tags={"stage": self.stage.label})

    def launch(self) -> None:
        self._autoscale_pool()
        while self.can_launch():
            block_ref, _meta = self.input_queue.popleft()
            seq = self._seq_in
            self._seq_in += 1
            if self._pool is not None:
                idx = min(range(len(self._pool)), key=lambda i: self._pool_load[i])
                out_ref, meta_ref = self._pool[idx].apply.options(
                    num_returns=2
                ).remote(block_ref)
                self._pool_load[idx] += 1
                self.in_flight[meta_ref] = (out_ref, idx, seq)
            else:
                out_ref, meta_ref = self._remote_fn.remote(
                    self.stage.block_fn, block_ref
                )
                self.in_flight[meta_ref] = (out_ref, None, seq)

    def collect_ready(self, ready_meta_refs: list) -> None:
        for meta_ref in ready_meta_refs:
            if meta_ref not in self.in_flight:
                continue
            out_ref, actor_idx, seq = self.in_flight.pop(meta_ref)
            if actor_idx is not None:
                self._pool_load[actor_idx] -= 1
            meta = self.api.get(meta_ref)
            self._pending_out[seq] = (out_ref, meta)
        while self._seq_out in self._pending_out:
            self.outputs.append(self._pending_out.pop(self._seq_out))
            self._seq_out += 1

    def shutdown(self) -> None:
        if self._pool:
            for a in self._pool:
                try:
                    self.api.kill(a)
                except Exception:
                    pass


def execute_plan(stages: list[Any], api=None) -> Iterator[tuple[Any, dict]]:
    """Run the lowered stage list; yield (block_ref, meta) of the final stage.

    ``api`` is the ray_tpu module (injectable for tests).
    """
    if api is None:
        import ray_tpu as api  # noqa: PLC0415

    ctx = DataContext.get_current()

    # Source stage → initial (ref, meta) stream.
    source = stages[0]
    if isinstance(source, InputData):
        pending_source: list = []
        initial = list(source.block_refs)  # already (ref, meta) pairs
    elif isinstance(source, Read):
        tasks = source.datasource.get_read_tasks(
            source.parallelism if source.parallelism > 0
            else ctx.default_parallelism
        )
        read_fn = api.remote(num_cpus=ctx.task_num_cpus, num_returns=2)(
            _run_read_task
        )
        pending_source = []
        initial = []
        for t in tasks:
            out_ref, meta_ref = read_fn.remote(t)
            pending_source.append((out_ref, meta_ref))
    else:
        raise TypeError(f"plan must start with Read/InputData, got {source}")

    rest = stages[1:]
    yield from _execute_chain(initial, pending_source, rest, ctx, api)


def _execute_chain(initial, pending_source, rest, ctx, api):
    # Split the chain at barriers: run the streaming segment up to the first
    # AllToAll, materialize, run the barrier fn, continue with the remainder.
    for i, st in enumerate(rest):
        if isinstance(st, AllToAll):
            upstream = list(
                _stream_segment(initial, pending_source, rest[:i], ctx, api)
            )
            shuffled = st.fn(upstream)
            yield from _execute_chain(shuffled, [], rest[i + 1:], ctx, api)
            return
    yield from _stream_segment(initial, pending_source, rest, ctx, api)


def _stream_segment(initial, pending_source, stages, ctx, api):
    """Streaming loop over map/limit stages (no barriers inside)."""
    limit_remaining: dict[int, int] = {}
    execs: list[_StageExec | LimitOp] = []
    n_map_stages = sum(1 for st in stages if isinstance(st, FusedMapStage))
    for st in stages:
        if isinstance(st, FusedMapStage):
            execs.append(_StageExec(st, ctx, api, n_stages=n_map_stages))
        elif isinstance(st, LimitOp):
            limit_remaining[id(st)] = st.limit
            execs.append(st)
        else:
            raise TypeError(f"unexpected stage {st}")

    map_execs = [e for e in execs if isinstance(e, _StageExec)]
    final_out: collections.deque = collections.deque()

    # feed initial materialized refs
    upstream_out = collections.deque(initial)
    # Source blocks release in submission order even though read tasks
    # complete in any order (deterministic block order, as above).
    source_pending = {
        meta_ref: (out_ref, i)
        for i, (out_ref, meta_ref) in enumerate(pending_source)
    }
    src_buffer: dict[int, tuple] = {}
    src_next = 0
    source_done = not source_pending

    slice_fn = api.remote(num_cpus=0, num_returns=2)(_slice_block)

    def route(queue_in: collections.deque, start_idx: int) -> None:
        """Push (ref, meta) pairs through limit stages until the next map
        stage (or the final output)."""
        items = list(queue_in)
        queue_in.clear()
        for ref, meta in items:
            idx = start_idx
            emitted = True
            cur = (ref, meta)
            while idx < len(execs):
                st = execs[idx]
                if isinstance(st, LimitOp):
                    rem = limit_remaining[id(st)]
                    if rem <= 0:
                        emitted = False
                        break
                    nrows = cur[1].get("num_rows", -1)
                    if nrows < 0:
                        nrows = api.get(
                            api.remote(num_cpus=0)(
                                lambda b: BlockAccessor(b).num_rows()
                            ).remote(cur[0])
                        )
                    if nrows > rem:
                        sliced_ref, meta_ref = slice_fn.remote(cur[0], 0, rem)
                        cur = (sliced_ref, api.get(meta_ref))
                        nrows = rem
                    limit_remaining[id(st)] -= nrows
                    idx += 1
                else:
                    st.input_queue.append(cur)
                    emitted = False
                    break
            if emitted:
                final_out.append(cur)

    try:
        while True:
            # 1. route source outputs into the chain
            if upstream_out:
                route(upstream_out, 0)
            # 2. move each map stage's outputs downstream
            for i, st in enumerate(execs):
                if isinstance(st, _StageExec) and st.outputs:
                    route(st.outputs, i + 1)
            # 3. launch work
            for st in map_execs:
                st.launch()
            # 4. drain final outputs to consumer
            while final_out:
                yield final_out.popleft()
            # 5. check termination / limits satisfied
            all_limits_hit = limit_remaining and all(
                v <= 0 for v in limit_remaining.values()
            )
            upstream_done = source_done
            for st in execs:
                if isinstance(st, _StageExec):
                    st.upstream_done = upstream_done
                    upstream_done = st.done or (
                        upstream_done and not st.input_queue and not st.in_flight
                        and not st.outputs
                    )
            if all_limits_hit:
                break
            if source_done and all(
                e.done for e in map_execs
            ) and not upstream_out and not final_out:
                break
            # 6. wait for something to finish
            wait_refs = list(source_pending.keys())
            for st in map_execs:
                wait_refs.extend(st.in_flight.keys())
            if not wait_refs:
                continue
            ready, _ = api.wait(
                wait_refs, num_returns=1, timeout=0.1, fetch_local=True
            )
            for meta_ref in ready:
                if meta_ref in source_pending:
                    out_ref, idx = source_pending.pop(meta_ref)
                    src_buffer[idx] = (out_ref, api.get(meta_ref))
                    while src_next in src_buffer:
                        upstream_out.append(src_buffer.pop(src_next))
                        src_next += 1
                    if not source_pending:
                        source_done = True
                else:
                    for st in map_execs:
                        st.collect_ready([meta_ref])
        while final_out:
            yield final_out.popleft()
    finally:
        for st in map_execs:
            st.shutdown()
