"""Experiment-tracker integrations (reference: python/ray/air/integrations/)."""

from ray_tpu.air.integrations.base import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    TBXLoggerCallback,
)

__all__ = [
    "Callback",
    "CSVLoggerCallback",
    "JsonLoggerCallback",
    "TBXLoggerCallback",
]
