from ray_tpu.dashboard.http_server import DashboardServer, start_dashboard

__all__ = ["DashboardServer", "start_dashboard"]
