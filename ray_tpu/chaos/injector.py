"""Fault injection: scheduled/predicated kills, RPC delays and drops.

One injector per process, configured from the environment (``RTPU_CHAOS`` =
JSON rule list, or ``RTPU_CHAOS_FILE`` = path to one), from config
(``chaos_enabled`` gates everything), or programmatically/over RPC
(``install``; the `chaos` CLI verb and ``ray_tpu.util.state.inject_chaos``
fan rules to every daemon and worker in a live cluster). The same rule
format drives unit tests, the recovery devbench, and live-cluster chaos
drills (reference capability: the reference's chaos-testing utilities —
RayletKiller / WorkerKillerActor in test_utils.py — generalized into a
declarative, cluster-deliverable schedule).

Rule schema (JSON object per rule; unknown keys are rejected)::

    {"point": "train.step",          # where the probe sits (see below)
     "action": "kill",               # kill | delay | drop | error
     "match": {"rank": 1},           # predicate: all keys must match the
                                     #  probe attrs; "method"/"node" values
                                     #  are regexes, ints/strs are equality
     "after_s": 2.0,                 # armed this long after install
     "at_step": 3,                   # train.step only: fire when step == N
     "prob": 1.0,                    # firing probability once matched
     "count": 1,                     # max firings (-1 = unlimited)
     "delay_s": 0.5,                 # delay action: added latency
     "mode": "exit",                 # kill: "exit" (os._exit) | "raise"
     "exit_code": 137,               # kill/exit: status to die with
     "mark": "/tmp/chaos_marks"}     # dir: write a timestamped marker
                                     #  just before applying (benches read
                                     #  the injection instant from it)

Probe points and their attrs:

- ``train.step``  — every ``session.report()``; attrs ``rank``, ``slice``,
  ``step``, ``restart``. Kill a worker (match rank) or a whole slice
  (match slice) mid-step; ``delay`` sleeps ``delay_s`` inside the matched
  worker's step = an injected STRAGGLER (watchdog/attribution drills).
- ``daemon.tick`` — the node daemon's heartbeat loop; attrs ``node``.
  Kill takes the daemon down abruptly (no deregistration) together with
  its worker processes — a node/slice death as the head sees one.
- ``rpc.server`` — every inbound control/transfer-plane RPC dispatch;
  attrs ``method``. ``delay`` sleeps before dispatch — inline on the
  connection's read loop, so frames queued behind the matched one wait
  too (TCP-stream delay semantics; scope the method regex with that in
  mind — a broad delay can age out heartbeats sharing the connection).
  ``drop`` swallows the request (the caller sees a timeout / hang,
  exactly like a lost datagram to a wedged peer).
- ``serve.replica`` — every serve data-plane request as it enters a
  replica (before the user callable); attrs ``deployment``, ``replica``,
  ``method`` (``method`` is a regex key). ``delay`` makes the replica a
  latency outlier (circuit-breaker food), ``error`` feeds
  consecutive-failure tracking, ``kill`` is a replica death mid-request
  (use ``mode="raise"`` on in-process runtimes — ``"exit"`` takes the
  whole interpreter). ``drop`` is not meaningful at a sync call site and
  is ignored.
- ``head.tick``   — the head server's health loop; attrs ``boot`` (the
  head's boot id — scope a drill to ONE head when several share an
  interpreter, as in-process test clusters do). ``kill`` takes the
  control plane down abruptly — background tasks cancelled, NO final
  WAL/snapshot flush beyond what group commit already wrote (crash
  semantics) — so restart must come back from the persisted WAL. Works
  for in-process heads (tests/devbench) and real head processes alike.
- ``partition``   — directional head⇄node network partition, probed in
  the RPC clients that carry head traffic (the daemon's head link and
  the head's per-daemon clients); attrs ``node`` (regex key),
  ``direction``. Rules carry their own ``direction`` field:
  ``"to_head"`` affects node→head frames (heartbeats, registrations,
  actor_failed), ``"from_head"`` affects head→node frames (place_actor,
  PG 2PC, profile fan-out), ``"both"`` (default) affects both. ``drop``
  silently discards matched frames (callers see hangs/timeouts — lost
  datagrams, NOT connection resets, so reconnect logic is exercised the
  hard way); ``delay`` stalls them ``delay_s``. Heal with `chaos clear`.

Kills are real: ``mode="exit"`` calls ``os._exit`` so the process dies
without cleanup (SIGKILL semantics). ``mode="raise"`` raises
:class:`ChaosKilled` instead — for in-process runtimes where taking the
whole interpreter down would kill the test too.

This module must stay stdlib-only (plus utils.config, itself stdlib-only):
it is imported from the RPC protocol layer of every process.
"""

from __future__ import annotations

import json
import os
import random
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any

# Fast-path gate: protocol dispatch checks this module attribute before
# paying for a decide() call. True only while at least one rule is
# installed (and chaos is enabled).
ACTIVE = False

_ALLOWED_KEYS = {
    "point", "action", "match", "after_s", "at_step", "prob", "count",
    "delay_s", "mode", "exit_code", "mark", "direction",
}
_ACTIONS = ("kill", "delay", "drop", "error")
_POINTS = ("train.step", "daemon.tick", "rpc.server", "serve.replica",
           "head.tick", "partition")
_DIRECTIONS = ("both", "to_head", "from_head")
_REGEX_KEYS = ("method", "node")


class ChaosKilled(BaseException):
    """Raised by a kill rule with mode="raise" (BaseException so a broad
    ``except Exception`` in the instrumented path can't swallow the
    injected death)."""


@dataclass
class ChaosRule:
    point: str
    action: str = "kill"
    match: dict[str, Any] = field(default_factory=dict)
    after_s: float = 0.0
    at_step: int | None = None
    prob: float = 1.0
    count: int = -1
    delay_s: float = 0.1
    mode: str = "exit"
    exit_code: int = 137
    mark: str | None = None
    # partition rules only: which head⇄node direction the rule severs.
    direction: str = "both"
    # runtime state
    fired: int = 0
    installed_ts: float = field(default_factory=time.monotonic)

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosRule":
        unknown = set(d) - _ALLOWED_KEYS
        if unknown:
            raise ValueError(f"unknown chaos rule keys: {sorted(unknown)}")
        rule = cls(**{k: v for k, v in d.items()})
        if rule.point not in _POINTS:
            raise ValueError(
                f"unknown chaos point {rule.point!r}; one of {_POINTS}")
        if rule.action not in _ACTIONS:
            raise ValueError(
                f"unknown chaos action {rule.action!r}; one of {_ACTIONS}")
        if rule.direction not in _DIRECTIONS:
            raise ValueError(
                f"unknown partition direction {rule.direction!r}; one of "
                f"{_DIRECTIONS}")
        return rule

    def to_dict(self) -> dict:
        return {
            "point": self.point, "action": self.action,
            "match": dict(self.match), "after_s": self.after_s,
            "at_step": self.at_step, "prob": self.prob, "count": self.count,
            "delay_s": self.delay_s, "mode": self.mode,
            "exit_code": self.exit_code, "mark": self.mark,
            "direction": self.direction,
            "fired": self.fired,
        }

    def matches(self, attrs: dict[str, Any]) -> bool:
        if self.at_step is not None and attrs.get("step") != self.at_step:
            return False
        for key, want in (self.match or {}).items():
            got = attrs.get(key)
            if key in _REGEX_KEYS:
                if got is None or not re.search(str(want), str(got)):
                    return False
            elif got != want:
                return False
        return True


_lock = threading.Lock()
_rules: list[ChaosRule] = []
_fired: list[dict] = []
_env_loaded = False
_FIRED_TAIL = 200


def _chaos_enabled() -> bool:
    try:
        from ray_tpu.utils.config import get_config

        return bool(get_config().chaos_enabled)
    except Exception:
        return True


def _refresh_active_locked() -> None:
    global ACTIVE
    ACTIVE = bool(_rules)


def _ensure_env_loaded() -> None:
    """Parse RTPU_CHAOS / RTPU_CHAOS_FILE once per process (workers inherit
    the daemon's environment at fork, so an env schedule set before cluster
    start reaches every process)."""
    global _env_loaded
    with _lock:
        if _env_loaded:
            return
        _env_loaded = True
        raw = os.environ.get("RTPU_CHAOS", "")
        path = os.environ.get("RTPU_CHAOS_FILE", "")
        if not raw and path:
            try:
                with open(path) as f:
                    raw = f.read()
            except OSError:
                raw = ""
        if not raw:
            return
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError:
            return
        for d in parsed if isinstance(parsed, list) else [parsed]:
            try:
                _rules.append(ChaosRule.from_dict(d))
            except (ValueError, TypeError):
                continue
        _refresh_active_locked()


def _rule_key(r: ChaosRule) -> tuple:
    return (r.point, r.action, tuple(sorted((r.match or {}).items())),
            r.after_s, r.at_step, r.prob, r.count, r.delay_s, r.mode,
            r.exit_code, r.mark, r.direction)


def install(rules: list[dict | ChaosRule], replace: bool = False) -> int:
    """Install rules into THIS process; returns the installed rule count.
    ``replace=True`` drops existing rules first. Exact duplicates of an
    installed rule that still has firing budget are skipped — the cluster
    fan-out (head → daemon → worker) visits a co-hosted test cluster's
    shared interpreter once per leg, and each leg must not multiply the
    budget. An EXHAUSTED duplicate does not block: re-running the same
    drill (`chaos kill-worker --rank 1` twice) arms a fresh firing, with
    the spent rule dropped so status stays readable."""
    _ensure_env_loaded()
    parsed = [r if isinstance(r, ChaosRule) else ChaosRule.from_dict(r)
              for r in rules or []]
    with _lock:
        if replace:
            _rules.clear()
        have = {_rule_key(r) for r in _rules
                if r.count < 0 or r.fired < r.count}
        for r in parsed:
            if _rule_key(r) in have:
                continue
            have.add(_rule_key(r))
            # Replace any exhausted twin instead of accumulating spent
            # rules forever.
            _rules[:] = [x for x in _rules if _rule_key(x) != _rule_key(r)]
            _rules.append(r)
        _refresh_active_locked()
        return len(_rules)


def clear() -> None:
    global _env_loaded
    with _lock:
        _rules.clear()
        _fired.clear()
        # A clear also suppresses re-loading the env schedule: `chaos clear`
        # must actually stop the chaos, even when RTPU_CHAOS is still set.
        _env_loaded = True
        _refresh_active_locked()


def remove_point(point: str) -> int:
    """Remove only the rules installed at one probe point (heal a
    partition without disarming the rest of a composed drill). Returns
    the number removed."""
    with _lock:
        before = len(_rules)
        _rules[:] = [r for r in _rules if r.point != point]
        _refresh_active_locked()
        return before - len(_rules)


def reset_for_tests() -> None:
    """Full reset incl. the env-loaded latch (test isolation only)."""
    global _env_loaded
    with _lock:
        _rules.clear()
        _fired.clear()
        _env_loaded = False
        _refresh_active_locked()


def status() -> dict:
    _ensure_env_loaded()
    with _lock:
        return {
            "pid": os.getpid(),
            "active": ACTIVE,
            "rules": [r.to_dict() for r in _rules],
            "fired": list(_fired),
        }


def fired(point: str | None = None) -> list[dict]:
    with _lock:
        return [f for f in _fired if point is None or f["point"] == point]


def decide(point: str, **attrs) -> ChaosRule | None:
    """First armed, matching, non-exhausted rule for ``point`` — consuming
    one firing from its budget — or None. Thread-safe."""
    _ensure_env_loaded()
    if not ACTIVE or not _chaos_enabled():
        return None
    now = time.monotonic()
    with _lock:
        for rule in _rules:
            if rule.point != point:
                continue
            if rule.count >= 0 and rule.fired >= rule.count:
                continue
            if now - rule.installed_ts < rule.after_s:
                continue
            if not rule.matches(attrs):
                continue
            if rule.prob < 1.0 and random.random() >= rule.prob:
                continue
            rule.fired += 1
            _fired.append({"point": point, "action": rule.action,
                           "ts": time.time(), "attrs": dict(attrs)})
            del _fired[:-_FIRED_TAIL]
            return rule
    return None


def write_mark(rule: ChaosRule, point: str, attrs: dict) -> str | None:
    """Timestamped marker file written at the injection instant (benches
    measure detection latency from it). Never fails the injection."""
    if not rule.mark:
        return None
    try:
        os.makedirs(rule.mark, exist_ok=True)
        path = os.path.join(
            rule.mark, f"chaos-{point.replace('.', '_')}-{time.time_ns()}")
        with open(path, "w") as f:
            json.dump({"ts": time.time(), "point": point,
                       "action": rule.action, "attrs": attrs}, f)
        return path
    except OSError:
        return None


def maybe_kill(point: str, **attrs) -> None:
    """Apply a matching kill/error/delay rule at a code-point inside the
    target process: exit hard (``mode="exit"``), raise :class:`ChaosKilled`
    / RuntimeError for in-process targets, or — for ``delay`` — sleep
    ``delay_s`` inline. At ``train.step`` a delay rule IS a straggler
    injection: the matched rank's step time stretches while its peers wait
    at the allreduce, exactly the fault the watchdog's step-drift detector
    and straggler attribution exist to catch."""
    rule = decide(point, **attrs)
    if rule is None:
        return
    write_mark(rule, point, attrs)
    if rule.action == "error":
        raise RuntimeError(f"chaos: injected error at {point} ({attrs})")
    if rule.action == "delay":
        time.sleep(max(0.0, float(rule.delay_s)))
        return
    if rule.action != "kill":
        return  # drop makes no sense at a kill probe; ignore
    if rule.mode == "raise":
        raise ChaosKilled(f"chaos: injected kill at {point} ({attrs})")
    os._exit(rule.exit_code)


def partition_action(node: str, direction: str) -> tuple[str, float] | None:
    """``partition`` probe for one frame of head⇄node traffic: returns
    ("drop", 0) / ("delay", seconds) or None. ``direction`` is the frame's
    travel direction ("to_head" | "from_head"); a rule severs it when its
    own direction is "both" or matches. Unlike :func:`decide` this does
    NOT log one firing per frame — a severed heartbeat stream would flood
    the firing log — it records only each rule's FIRST firing (the
    injection instant benches measure from) while still counting every
    frame against a finite budget."""
    _ensure_env_loaded()
    if not ACTIVE or not _chaos_enabled():
        return None
    now = time.monotonic()
    with _lock:
        for rule in _rules:
            if rule.point != "partition":
                continue
            if rule.direction != "both" and rule.direction != direction:
                continue
            if rule.count >= 0 and rule.fired >= rule.count:
                continue
            if now - rule.installed_ts < rule.after_s:
                continue
            if not rule.matches({"node": node}):
                continue
            if rule.prob < 1.0 and random.random() >= rule.prob:
                continue
            rule.fired += 1
            if rule.fired == 1:
                _fired.append({"point": "partition", "action": rule.action,
                               "ts": time.time(),
                               "attrs": {"node": node,
                                         "direction": direction}})
                del _fired[:-_FIRED_TAIL]
                write_mark(rule, "partition",
                           {"node": node, "direction": direction})
            if rule.action == "drop":
                return ("drop", 0.0)
            if rule.action == "delay":
                return ("delay", max(0.0, float(rule.delay_s)))
            return None
    return None


def rpc_server_action(method: str) -> tuple[str, float] | None:
    """rpc.server probe: returns ("drop", 0) / ("delay", seconds) or None.
    The dispatch loop applies the action (it owns the event loop)."""
    rule = decide("rpc.server", method=method)
    if rule is None:
        return None
    write_mark(rule, "rpc.server", {"method": method})
    if rule.action == "drop":
        return ("drop", 0.0)
    if rule.action == "delay":
        return ("delay", max(0.0, float(rule.delay_s)))
    return None


# Load any env-provided schedule NOW: every probe site guards on the ACTIVE
# module flag before calling in, so the flag must be correct from import —
# a lazy-only load would leave an env schedule invisible forever.
_ensure_env_loaded()
