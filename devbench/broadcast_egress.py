"""Broadcast relay egress accounting + box-bandwidth ceiling proof.

PERF.json's object_store_broadcast row lands far under the reference's
2.99 GB/s 50-node number on this small shared build box. This script
separates the possible causes:

1. The fan-out doesn't parallelize (a real defect): the SOURCE would serve
   ~every pull itself and later pullers would wait on whole-object seals.
2. The box is bandwidth-bound (expected here): referrals spread across
   serving copies — including PARTIAL, mid-transfer copies served
   cut-through against their sealed-range watermark — and the measured
   aggregate approaches the box's own memcpy/loopback ceiling, meaning the
   plane is doing its job and the row is hardware-limited.

Two modes are measured:
- DEFAULT: the production path on this topology — co-hosted "nodes" share
  a boot id, so pullers map the holder's arena directly (plasma-style
  same-host sharing) and pay zero wire transfer.
- TCP-FORCED (RTPU_TRANSFER_SAME_HOST_ARENA=0): every pull rides the
  native range engine — cut-through relaying, pipelined multi-source
  range pulls, per-source referral budgets. This is the cross-host
  (real cluster) behavior; referral_counts/distinct_serving_copies come
  from this run.

Emits one JSON object (see `analysis` for the interpretation).

Reference anchor: src/ray/object_manager/push_manager.h bounds concurrent
chunk pushes at the source the same way the owner's referral budget does.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZE = 64 * 1024 * 1024
N_NODES = 4
N_PULLS = 8


def measure_memcpy() -> float:
    # bytes(bytearray) forces a real copy (bytes(bytes) is a no-op alias).
    buf = bytearray(SIZE)
    _ = bytes(buf)  # fault pages in
    best = 0.0
    for _trial in range(3):
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < 0.5:
            _ = bytes(buf)
            n += 1
        best = max(best, n * SIZE / (time.perf_counter() - t0))
    return best / 1e9


def measure_loopback() -> float:
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    payload = b"x" * (4 * 1024 * 1024)
    rounds = SIZE // len(payload)
    got = []

    def rx():
        conn, _ = srv.accept()
        total = 0
        while total < SIZE:
            b = conn.recv(1 << 20)
            if not b:
                break
            total += len(b)
        got.append(total)
        conn.close()

    t = threading.Thread(target=rx)
    t.start()
    cli = socket.create_connection(("127.0.0.1", port))
    t0 = time.perf_counter()
    for _ in range(rounds):
        cli.sendall(payload)
    cli.close()
    t.join()
    dt = time.perf_counter() - t0
    srv.close()
    return got[0] / dt / 1e9


def run_mode(force_tcp: bool) -> dict:
    """One full cluster measurement in a SUBPROCESS: the same-host switch
    must be fixed before any daemon/worker forks, and the two modes must
    not share warmed caches."""
    code = r'''
import json, sys, time
import numpy as np
import ray_tpu
from ray_tpu import remote
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.worker import global_worker
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
from ray_tpu.utils.ids import JobID

SIZE, N_NODES, N_PULLS = %d, %d, %d

c = Cluster()
# single pull: two dedicated nodes, warm connections
n1 = c.add_node(num_cpus=1, node_id="egress-sp-a")
n2 = c.add_node(num_cpus=1, node_id="egress-sp-b")
rt_a = c.connect(n1)
rt_b = c.connect(n2)
ref = rt_a.put(b"z" * SIZE)
rt_b.get([ref], timeout=120)  # cold (connection setup)
bytes_best = nd_best = 0.0
for i in range(3):
    r = rt_a.put(b"y" * SIZE)  # fresh object id per put: a real re-pull
    t0 = time.perf_counter()
    rt_b.get([r], timeout=120)
    bytes_best = max(bytes_best, SIZE / (time.perf_counter() - t0))
    r = rt_a.put(np.full(SIZE, 7, np.uint8))
    t0 = time.perf_counter()
    (arr,) = rt_b.get([r], timeout=120)
    nd_best = max(nd_best, SIZE / (time.perf_counter() - t0))
    assert int(arr[0]) == 7
    assert arr.flags.writeable is False  # read-only get() contract
    del arr
rt_b.shutdown()
rt_a.shutdown()

src = c.add_node(num_cpus=1, node_id="egress-src")
for i in range(N_NODES):
    c.add_node(num_cpus=2, node_id="egress-%%d" %% i)
rt = c.connect(src)
global_worker.runtime = rt
global_worker.worker_id = rt.worker_id
global_worker.node_id = rt.node_id
global_worker.job_id = JobID.from_random()
global_worker.mode = "cluster"

@remote
def consume(blob):
    return len(blob)

def fan_out():
    big = ray_tpu.put(b"b" * SIZE)
    refs = [consume.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="egress-%%d" %% (i %% N_NODES)), num_cpus=1).remote(big)
        for i in range(N_PULLS)]
    t0 = time.perf_counter()
    out = ray_tpu.get(refs, timeout=600)
    dt = time.perf_counter() - t0
    assert out == [SIZE] * N_PULLS
    return big, dt

fan_out()  # warm worker forks
best = None
for _ in range(3):
    big, dt = fan_out()
    if best is None or dt < best[1]:
        best = (big, dt)
big, dt = best
counts = {k[:8]: v for k, v in rt.refer_counts.get(big.id, {}).items()}
src_key = rt.worker_id.hex()[:8]
total_refs = sum(counts.values()) or 1
out = {
    "wall_s": round(dt, 3),
    "aggregate_GBps": round(N_PULLS * SIZE / dt / 1e9, 3),
    "referral_counts": counts,
    "source_copy": src_key,
    "source_share": round(counts.get(src_key, 0) / total_refs, 3),
    "distinct_serving_copies": len(counts),
    "single_pull_GBps": round(bytes_best / 1e9, 3),
    "single_pull_ndarray_GBps": round(nd_best / 1e9, 3),
}
rt.shutdown()
c.shutdown()
print("RESULT " + json.dumps(out))
''' % (SIZE, N_NODES, N_PULLS)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["RTPU_WORKER_IDLE_TTL_S"] = "300"
    if force_tcp:
        env["RTPU_TRANSFER_SAME_HOST_ARENA"] = "0"
    else:
        env.pop("RTPU_TRANSFER_SAME_HOST_ARENA", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=1200)
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(
        f"mode run failed (rc {proc.returncode}):\n{proc.stderr[-2000:]}")


def main() -> None:
    memcpy_gbps = measure_memcpy()
    loopback_gbps = measure_loopback()
    tcp = run_mode(force_tcp=True)
    default = run_mode(force_tcp=False)

    result = {
        "object_mb": SIZE // (1 << 20),
        "pulls": N_PULLS,
        "nodes": N_NODES,
        # Headline numbers: the production path for this (one-host)
        # topology — same-host arena reads.
        "wall_s": default["wall_s"],
        "aggregate_GBps": default["aggregate_GBps"],
        "single_pull_GBps": default["single_pull_GBps"],
        "single_pull_ndarray_GBps": default["single_pull_ndarray_GBps"],
        # Relay/cut-through machinery, measured with same-host reads OFF
        # (what a real multi-host cluster runs).
        "referral_counts": tcp["referral_counts"],
        "source_copy": tcp["source_copy"],
        "source_share": tcp["source_share"],
        "distinct_serving_copies": tcp["distinct_serving_copies"],
        "tcp_plane": {
            "wall_s": tcp["wall_s"],
            "aggregate_GBps": tcp["aggregate_GBps"],
            "single_pull_GBps": tcp["single_pull_GBps"],
            "single_pull_ndarray_GBps": tcp["single_pull_ndarray_GBps"],
        },
        "memcpy_GBps": round(memcpy_gbps, 3),
        "loopback_GBps": round(loopback_gbps, 3),
        "analysis": (
            "Cut-through + pipelined multi-source pulls (this PR): the "
            "transfer server serves [offset, offset+len) range requests "
            "against each object's sealed-range watermark, so a relay "
            "node feeds downstream pullers WHILE its own pull is in "
            "flight (no store-and-forward); pullers split each object "
            "into ranges fetched from every referred copy (full or "
            "partial) with per-connection request pipelining, and the "
            "owner budgets in-flight referrals per source "
            "(distinct_serving_copies > 2 shows the spread; pullers "
            "advertise as partial sources before their first byte "
            "lands). On THIS one-host topology the default plane goes "
            "further: co-hosted node arenas are mapped directly (boot-id "
            "match) and get() is served from a pinned read-only view — "
            "zero wire bytes, which is why the headline aggregate beats "
            "the tcp_plane one. Ceilings measured on this box bound "
            "both: a bytes get() pays exactly one materialization "
            "memcpy (single_pull_GBps -> memcpy_GBps), ndarray get() "
            "pays none (read-only plasma semantics, now on every Python "
            "version), and the TCP aggregate pays recv+deserialize "
            "copies per delivered byte against a shared-core loopback "
            "ceiling (loopback_GBps). The box is a noisy 2-core VM "
            "(ceilings swing ~2x between runs); all rows are best-of-3."
        ),
    }
    print(json.dumps(result, indent=2))
    with open("PERF_BROADCAST_EGRESS.json", "w") as f:
        json.dump(result, f, indent=2)


if __name__ == "__main__":
    main()
