"""Negative fixture: the same shapes as the bad fixtures, done right —
rtlint must report ZERO findings here (false-positive canary)."""

import asyncio
import json
import threading
from collections import deque

from ray_tpu.devtools.annotations import guarded_by


@guarded_by("_lock", "_window", "_seq_no")
class CleanWindow:
    def __init__(self):
        self._lock = threading.Lock()
        self._window = deque(maxlen=128)
        self._seq_no = 0
        self._thread = threading.Thread(target=self._flush_loop,
                                        daemon=True)
        self._thread.start()

    def report(self, step_time: float) -> int:
        with self._lock:
            self._window.append(step_time)
            self._seq_no += 1
            return self._seq_no

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                snapshot = list(self._window)
            _ = json.dumps(snapshot)

    async def publish(self):
        with self._lock:
            snapshot = list(self._window)
        await asyncio.sleep(0)  # no lock held across the suspension
        return snapshot
