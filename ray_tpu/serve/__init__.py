"""ray_tpu.serve: online serving over replica actors.

Capability parity with the reference's ray.serve (reference:
python/ray/serve/ — controller _private/controller.py:121, deployment state
FSM _private/deployment_state.py:2278, pow-2 router
_private/request_router/pow_2_router.py:27, replica _private/replica.py:1812,
long-poll _private/long_poll.py, batching batching.py, HTTP proxy
_private/proxy.py:1605).
"""

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    grpc_port,
    http_port,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.grpc_proxy import GrpcRequest
from ray_tpu.serve.http_proxy import Request, Response
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.resilience import (
    CircuitBreakerConfig,
    DeadlineExceeded,
    Overloaded,
    RetryPolicy,
    current_deadline as request_deadline,
)

__all__ = [
    "deployment", "Deployment", "Application",
    "run", "start", "shutdown", "status", "delete",
    "get_app_handle", "get_deployment_handle", "http_port", "grpc_port",
    "GrpcRequest",
    "DeploymentHandle", "DeploymentResponse",
    "AutoscalingConfig", "DeploymentConfig",
    "batch", "Request", "Response",
    "multiplexed", "get_multiplexed_model_id",
    "Overloaded", "DeadlineExceeded", "RetryPolicy",
    "CircuitBreakerConfig", "request_deadline",
]

# usage telemetry (local-only, opt-out — reference: usage_lib auto-records
# library imports)
try:
    from ray_tpu.usage import record_library_usage as _rec
    _rec("serve")
except Exception:
    pass
