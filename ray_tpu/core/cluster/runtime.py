"""ClusterRuntime: the per-process core-worker library for cluster mode.

Capability parity with the reference's core_worker (reference:
src/ray/core_worker/core_worker.cc — SubmitTask :1957 lease-based submission
with worker reuse via NormalTaskSubmitter, Put :971 / Get :1290 owner-based
object resolution, SubmitActorTask :2372 direct gRPC to the actor's worker):
every process (driver or pooled worker) instantiates one ClusterRuntime. It
owns a local object store, serves object fetches to peers, submits tasks via
node-daemon leases, and talks to the head for actors/KV/named entities.

Hot-path design (reference: normal_task_submitter.cc's event-driven submit
loop — no thread per task): all submission state lives on the process's io
event loop. ``submit_task`` serializes on the caller thread, then hands the
task to a per-scheduling-key state machine on the loop which leases workers
(bounded pending lease requests), pipelines pushes over per-worker
connections, and resubmits on worker failure. Actor calls ride a per-actor
state machine with FIFO dispatch on one connection (reference:
sequential_actor_submit_queue ordering).

Object protocol: the submitting worker *owns* task returns. Small results
ride inline in the task reply and are stored at the owner (reference:
max_direct_call_object_size); large results stay at the executor, the owner
records the location, and readers fetch from the holder.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
import uuid
from collections import deque
from typing import Any

from ray_tpu.core.cluster.protocol import (
    AsyncRpcClient,
    EventLoopThread,
    RpcClient,
    RpcConnectionLost,
    RpcError,
    RpcServer,
    spawn_task,
)
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    LeaseTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.object_ref import ObjectRef, refcounting_suppressed
from ray_tpu.core.store import LocalObjectStore, ReferenceCounter
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.utils import serialization
from ray_tpu.utils.config import get_config
from ray_tpu.utils.ids import ActorID, NodeID, ObjectID, WorkerID

import cloudpickle


# Control-plane byte accounting (lazy: the registry must not import-cost the
# hot path). Tags: kind = task|actor for pushes, op = export|fetch|hit for
# registry traffic. These flush to the head with every telemetry push, so
# devbench/control_plane.py can show per-task wire bytes cluster-wide.
_ctrl_metrics = None


def ctrl_metrics():
    global _ctrl_metrics
    if _ctrl_metrics is None:
        from ray_tpu.util.metrics import Counter

        _ctrl_metrics = (
            Counter("ctrl_push_bytes",
                    "serialized task-spec bytes pushed to executors",
                    tag_keys=("kind",)),
            Counter("ctrl_push_count", "task specs pushed to executors",
                    tag_keys=("kind",)),
            Counter("ctrl_fn_bytes",
                    "definition bytes through the function registry",
                    tag_keys=("op",)),
            Counter("ctrl_fn_count", "function registry operations",
                    tag_keys=("op",)),
        )
    return _ctrl_metrics


def observe_ctrl_push(kind: str, nbytes: int) -> None:
    try:
        push_b, push_c, _, _ = ctrl_metrics()
        push_b.inc(float(nbytes), tags={"kind": kind})
        push_c.inc(1.0, tags={"kind": kind})
    except Exception:
        pass  # metrics must never fail a submit


def observe_ctrl_fn(op: str, nbytes: int) -> None:
    try:
        _, _, fn_b, fn_c = ctrl_metrics()
        fn_b.inc(float(nbytes), tags={"op": op})
        fn_c.inc(1.0, tags={"op": op})
    except Exception:
        pass


class _LeasedWorker:
    __slots__ = ("lease_id", "worker_id", "addr", "client", "inflight",
                 "idle_since", "daemon", "dead", "served")

    def __init__(self, lease_id: str, worker_id: str, addr: tuple[str, int],
                 client: AsyncRpcClient, daemon: AsyncRpcClient):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.addr = addr
        self.client = client
        self.daemon = daemon  # grantor, for return_lease
        self.inflight = 0
        self.idle_since = 0.0  # monotonic ts when inflight last hit 0
        self.dead = False
        self.served = 0  # tasks dispatched over this lease's lifetime


class _TaskItem:
    __slots__ = ("spec", "blob", "return_ids", "attempts")

    def __init__(self, spec: TaskSpec, blob: bytes, return_ids):
        self.spec = spec
        self.blob = blob
        self.return_ids = return_ids
        self.attempts = 0


class _KeyState:
    """Per-scheduling-key submitter state (reference: one queue per
    SchedulingKey in normal_task_submitter.h:52). Loop-thread-only."""

    __slots__ = ("key", "resources", "env_hash", "queue", "workers",
                 "pending_leases", "lease_rpcs", "strategy", "spread_idx")

    def __init__(self, key, resources, env_hash, strategy=None):
        self.key = key
        self.resources = resources
        self.env_hash = env_hash
        self.queue: deque[_TaskItem] = deque()
        self.workers: list[_LeasedWorker] = []
        self.pending_leases = 0  # WORKERS requested in flight (not RPCs)
        self.lease_rpcs = 0      # outstanding lease RPCs
        self.strategy = strategy   # SchedulingStrategy (None = DEFAULT)
        self.spread_idx = 0        # SPREAD round-robin cursor


_SENT_CALL_LOST = (
    "actor restarted; this call was in flight on the dead incarnation and "
    "may have executed there (actor calls are at-most-once)")


class _ActorState:
    """Per-actor submitter (reference: actor_task_submitter.cc). FIFO
    dispatch over one pipelined connection. Failed in-flight calls gather
    in ``retrying`` while recovery runs, then FAIL with ActorDiedError —
    they were sent to the dead incarnation and may have executed there
    (at-most-once; see _actor_recover). Only never-sent ``pending`` calls
    flow to a restarted incarnation. Loop-thread-only."""

    __slots__ = ("actor_id", "client", "addr", "pending", "inflight",
                 "resolving", "window", "retrying", "recovering")

    def __init__(self, actor_id: str):
        self.actor_id = actor_id
        self.client: AsyncRpcClient | None = None
        self.addr: tuple[str, int] | None = None
        self.pending: deque[_TaskItem] = deque()
        self.inflight = 0
        self.resolving = False
        self.window = 256
        self.retrying: list[_TaskItem] = []
        self.recovering = False


class ClusterRuntime:
    """Runtime interface implementation backed by the cluster."""

    # Pipelined pushes per leased worker: the worker executes serially, so
    # depth>1 only hides RPC latency (reference: lease reuse for queued tasks
    # of the same key).
    PIPELINE_DEPTH = 16
    # Outstanding lease requests per key: bounds daemon fork storms while
    # still scaling out under sustained queue depth (reference:
    # max_pending_lease_requests_per_scheduling_category).
    MAX_PENDING_LEASES = 4

    # Results below this size travel inline / in the process-local store;
    # larger blobs go through the node's shared-memory arena when available
    # (reference: plasma for non-inline objects).
    SHM_THRESHOLD = 32 * 1024
    # Lineage retention budget (reference: RAY_max_lineage_bytes).
    MAX_LINEAGE_BYTES = 64 * 1024 * 1024

    def __init__(self, head_host: str, head_port: int,
                 node_daemon_addr: tuple[str, int] | None = None,
                 is_worker: bool = False, shm_name: str | None = None):
        self.worker_id = WorkerID.from_random()
        self.node_id = NodeID.from_random()
        self.is_worker = is_worker
        self.store = LocalObjectStore()
        self.refs = ReferenceCounter(on_release=self._release_object)
        # Attach the node's shm arena (created by the node daemon).
        self.shm = None
        shm_name = shm_name or os.environ.get("RTPU_SHM_NAME")
        if shm_name:
            try:
                from ray_tpu.core.shm_store import SharedMemoryStore

                self.shm = SharedMemoryStore(shm_name, create=False)
            except Exception:
                self.shm = None
        self._locations: dict[ObjectID, str] = {}  # owned oid -> holder worker hex
        self._location_sizes: dict[ObjectID, int] = {}  # oid -> bytes (if known)
        # One-to-many distribution (reference: push_manager.h relay trees;
        # here pull-based): owner tracks every worker that CACHED a copy of
        # a large owned object and refers new pullers round-robin across
        # all copies, with a bounded number of outstanding referrals so the
        # source's egress stays bounded under a simultaneous fan-out.
        self._replicas: dict[ObjectID, set[str]] = {}
        # Workers whose pull of an owned object is still IN FLIGHT: their
        # nodes serve landed ranges cut-through against the sealed-range
        # watermark, so they count as (partial) serving copies for the
        # multi-source range engine (reference: push_manager.h starts
        # chunked pushes before the whole object arrives at a relay).
        self._partials: dict[ObjectID, set[str]] = {}
        self._reported_holder: dict[ObjectID, str] = {}  # oid -> owner hex
        self._borrow_cache: dict[ObjectID, float] = {}  # released-borrow ts
        # Borrowed copies promoted to primary by the owner after it lost its
        # own copy: exempt from the TTL sweep until owner-freed. The lock
        # makes pin-vs-sweep atomic (pin handler runs on the io loop, the
        # sweep on caller threads).
        self._pinned_borrows: set[ObjectID] = set()
        self._borrow_lock = threading.Lock()
        # Per-source outstanding referral stamps (bounded in-flight pulls
        # per serving copy): oid -> {worker hex -> [issue ts, ...]}.
        self._referrals: dict[ObjectID, dict[str, list[float]]] = {}
        # Outstanding referral GRANTS (ts, [sources charged]): freeing a
        # slot must uncharge every source the grant stamped, or k-source
        # referrals leak k-1 phantom in-flight entries per pull until the
        # TTL and the budget throttles idle copies.
        self._referral_grants: dict[ObjectID, deque] = {}
        self.refer_counts: dict[ObjectID, dict[str, int]] = {}  # observability
        # Extra serving copies (worker hexes) for the pull currently in
        # flight on a caller thread, stashed between the owner's referral
        # and the native multi-source pull.
        self._pull_extra: dict[ObjectID, tuple] = {}
        self._io = EventLoopThread.get()
        self.head = RpcClient(head_host, head_port)
        self._head_host, self._head_port = head_host, head_port
        self.node_daemon_addr = node_daemon_addr
        self._daemon = RpcClient(*node_daemon_addr) if node_daemon_addr else None
        # Submission state machines — touched only from the io loop thread.
        self._key_states: dict[tuple, _KeyState] = {}
        # Cross-thread submission buffer (drained on the loop in one wakeup).
        self._submit_buf: deque[_TaskItem] = deque()
        self._submit_wake = False
        self._submit_lock = threading.Lock()
        self._actor_sm: dict[str, _ActorState] = {}
        # task_id hex -> ("queued", _KeyState) | ("running", _LeasedWorker)
        self._task_where: dict[str, tuple] = {}
        self._apeers: dict[tuple[str, int], AsyncRpcClient] = {}
        self._peer_clients: dict[tuple[str, int], RpcClient] = {}
        self._peer_lock = threading.Lock()
        self._actor_addr_cache: dict[str, tuple[str, int]] = {}
        self._holder_nodes: dict[str, str] = {}  # worker hex -> node hex
        # worker hex -> (ts, addr, node): short-TTL directory cache — the
        # pull hot path resolved the same holder through the head per get,
        # and those round trips dwarfed the wire time of warm pulls.
        self._worker_dir_cache: dict[str, tuple[float, tuple | None, str]] = {}
        # Mapped peer-node arenas for same-host zero-copy reads
        # (shm name -> attached SharedMemoryStore).
        self._peer_arenas: dict[str, Any] = {}
        self._nodes_cache: tuple[float, dict] | None = None  # (ts, nodes)
        self._xfer_cache = None  # (ts, {node_id: transfer_addr})
        self._actor_states: dict[str, str] = {}
        # Definitions this process already exported to the head registry
        # (idempotence cache — reference: function_manager's exported set).
        self._exported_fns: set[str] = set()
        self._cancelled: set[str] = set()  # task_id hex
        # Lineage retention for reconstruction (reference:
        # task_manager.h:184 lineage kept while returns are referenced;
        # object_recovery_manager.h:41 resubmits the creating task when a
        # stored copy is lost). task_id hex -> (spec, blob, live return count).
        self._lineage: dict[str, list] = {}
        self._lineage_bytes = 0
        self._recovering: set[ObjectID] = set()
        self._recovery_attempts: dict[ObjectID, int] = {}
        self._recovery_lock = threading.Lock()
        self._shutdown = False
        # Wakes wait()/get() when results land (event-driven wait; the
        # reference wakes waiters from the in-memory store's seal path).
        self._wait_cond = threading.Condition()
        self.store.on_seal = self._notify_waiters

        # Serve object fetches (and, for workers, task execution) to peers.
        self.server = RpcServer("127.0.0.1", 0)
        self.server.register("get_object", self._handle_get_object)
        self.server.register("get_object_chunk", self._handle_get_object_chunk)
        self.server.register("free_object", self._handle_free_object)
        self.server.register("report_location", self._handle_report_location)
        self.server.register("report_lost", self._handle_report_lost)
        self.server.register("report_holder", self._handle_report_holder)
        self.server.register("pin_object", self._handle_pin_object)
        self.server.register("ping", self._handle_ping)
        # Profiling one-shots answered by EVERY cluster process (driver and
        # worker alike): the `stack <worker>` / `memory --device` verbs
        # resolve any row of the head's worker directory.
        self.server.register("dump_stack", self._handle_dump_stack)
        self.server.register("memory_snapshot", self._handle_memory_snapshot)
        self.server.register("chaos_install", self._handle_chaos_install)
        # Compiled-graph direct channels: peer writers push dataflow frames
        # straight at the reader's server (ray_tpu/dag/direct.py). Raw
        # dispatch (enqueue-only, reader thread acks); the dag import is
        # deferred to the first frame so processes that never run a
        # compiled graph don't pay the package import.
        self.server.register_raw("dag_chan_push", self._handle_dag_chan_push)
        self.addr = self._io.run(self.server.start())
        # Workers learn their node from the forking daemon's env; a DRIVER
        # asks its attached daemon — without this, objects the driver holds
        # can't be served over the node's native transfer plane (pullers
        # couldn't map our worker id to a node).
        my_node = os.environ.get("RTPU_NODE_ID", "")
        if not my_node and self._daemon is not None:
            try:
                my_node = self._daemon.call("node_info",
                                            timeout=10).get("node_id", "")
            except Exception:
                my_node = ""
        self.my_node_id = my_node
        # Naturally idempotent (same row every time) → safe to retry
        # through a head outage at process start.
        self.head.call_retrying("register_worker", idempotent=True,
                                worker_id=self.worker_id.hex(),
                                host=self.addr[0], port=self.addr[1],
                                node_id=my_node)
        self._reaper_task = self._io.spawn(self._lease_reaper())
        # Telemetry flusher: EVERY cluster process (driver and worker alike)
        # periodically pushes its metrics snapshot, new finished spans, and
        # drained task events to the head in one batched RPC (reference:
        # TaskEventBuffer flushing into GcsTaskManager + the metrics agent's
        # push — never on the hot path, bounded batches, drop-oldest).
        self._stop_flush = threading.Event()
        self._span_cursor = 0
        self._series_sampler = None  # lazy watchdog SeriesSampler
        threading.Thread(target=self._telemetry_flusher, daemon=True,
                         name="telemetry-flush").start()
        # Actor state invalidation via pubsub (single events or the head's
        # window-coalesced batches — both land in _on_pub).
        self.head.aio.on_notify("pub", self._on_pub)
        self.head.aio.on_notify("pub_batch", self._on_pub_batch)
        self.head.call_retrying("subscribe", idempotent=True,
                                channel="actor_events")

        def _on_head_reconnect():
            # A restarted head rebuilt its tables from its snapshot; refresh
            # anything connection-scoped (worker directory row, pubsub subs).
            # A non-persistent head came back EMPTY: drop the export cache
            # so the next submit of each definition re-exports it.
            self._exported_fns.clear()
            try:
                self.head.call("register_worker",
                               worker_id=self.worker_id.hex(),
                               host=self.addr[0], port=self.addr[1],
                               node_id=os.environ.get("RTPU_NODE_ID", ""))
                self.head.call("subscribe", channel="actor_events")
            except Exception:
                pass

        self.head.on_reconnect = _on_head_reconnect

    # ------------------------------------------------------------------ telemetry
    def _telemetry_flusher(self) -> None:
        from ray_tpu.core.events import global_event_buffer
        from ray_tpu.util import metrics, tracing

        buf = global_event_buffer()
        # Stable per-process source id: a daemon co-hosted with a driver
        # (local-cluster mode) reports the same registry — keying by
        # (node, pid) makes the second reporter overwrite, not double-count.
        source = f"{self.my_node_id or 'driver'}:{os.getpid()}"
        last_snapshot: dict | None = None
        last_sent = 0.0
        keep_cursor = 0  # head keep-gossip high-water mark
        while not self._stop_flush.is_set():
            period = get_config().telemetry_flush_interval_s
            self._stop_flush.wait(period if period > 0 else 0.5)
            if self._stop_flush.is_set() or self._shutdown:
                return
            if period <= 0:
                continue  # telemetry push disabled
            goodput_leg = None
            try:
                # A node daemon co-hosted in this process (local-cluster /
                # in-process test clusters) already reports this process's
                # buffer+registry — a second reporter would double-ship
                # spans and split events.
                from ray_tpu.core.cluster import node_daemon as _nd

                if _nd._process_telemetry_owner is not None:
                    continue
                events = buf.drain_dicts()
                spans, self._span_cursor = tracing.flush_new(
                    self._span_cursor)
                snapshot = metrics.registry().snapshot()
                # Straggler feed: per-rank step-time/sync-time deciles from
                # any train context living in this process ride the same
                # push (train/session.py collects; the head keys them by
                # source so restarts overwrite, not duplicate).
                train_stats = None
                try:
                    from ray_tpu.train import session as _session

                    train_stats = _session.collect_train_stats() or None
                except Exception:
                    pass
                # Watchdog series: delta-encoded hot-path samples derived
                # from the snapshot, piggybacked on the same push (the
                # sampler returns None when nothing changed).
                from ray_tpu.observability import sampler as _wd_sampler

                self._series_sampler, series = _wd_sampler.collect_for_flush(
                    self._series_sampler, snapshot)
                # Goodput events (restart downtime etc.) buffered in this
                # process piggyback the same push; requeued on failure and
                # id-deduplicated head-side, so delivery is at-least-once
                # with exactly-once accounting.
                goodput_leg = None
                try:
                    from ray_tpu.observability import goodput as _gp

                    goodput_leg = _gp.collect_for_flush()
                except Exception:
                    pass
                # Tail-sampling keeps piggyback the same push (no new
                # RPC): locally-decided keeps ship up, and the head's
                # reply gossips back every keep decided anywhere since
                # our cursor so fragments of a kept trace held HERE get
                # promoted too.
                keeps = tracing.drain_keeps()
                # Idle-process economy: nothing new to report and the
                # snapshot unchanged — skip the RPC, but keepalive well
                # inside the head's 60s liveness window so the source
                # doesn't age out of the federated export.
                now = time.monotonic()
                if not events and not spans and snapshot == last_snapshot \
                        and train_stats is None and series is None \
                        and goodput_leg is None and not keeps \
                        and now - last_sent < 20.0:
                    continue
                try:
                    reply = self.head.call(
                        "report_telemetry", source=source,
                        node_id=self.my_node_id, timeout=10,
                        snapshot=snapshot, spans=spans, events=events,
                        dropped=buf.dropped, train_stats=train_stats,
                        series=series, goodput=goodput_leg,
                        keeps=keeps, keep_cursor=keep_cursor)
                except Exception:
                    # Head outage with keeps drained: requeue them — the
                    # trace stays promotable (partial) once the head
                    # returns, instead of silently losing the verdict.
                    if keeps:
                        tracing.requeue_keeps(keeps)
                    raise
                _wd_sampler.handle_flush_reply(self._series_sampler, reply)
                goodput_leg = None  # delivered — don't requeue below
                if isinstance(reply, dict):
                    tracing.apply_keeps(reply.get("keeps") or ())
                    keep_cursor = int(reply.get("keep_cursor",
                                                keep_cursor))
                last_snapshot, last_sent = snapshot, now
            except Exception:
                # Head temporarily unreachable: events/spans drop (bounded
                # loss), but gauge samples must RE-send once it returns —
                # a transition lost here would otherwise read stale on the
                # head until the value next changes.
                try:
                    from ray_tpu.observability import sampler as _wd_sampler

                    _wd_sampler.handle_flush_failure(self._series_sampler)
                except Exception:
                    pass
                # Goodput events are NOT drop-tolerant (each is a whole
                # outage's accounting): requeue for the next flush.
                if goodput_leg:
                    try:
                        from ray_tpu.observability import goodput as _gp

                        _gp.flush_failed(goodput_leg)
                    except Exception:
                        pass

    def get_telemetry(self) -> dict:
        """The head's per-node telemetry table (source -> node/snapshot)."""
        return self.head.call("get_telemetry")

    def cluster_spans(self) -> list[dict]:
        """Finished spans flushed to the head from every node."""
        return self.head.call("get_spans").get("spans", [])

    # ----------------------------------------------------------- profiling
    def profile_cluster(self, seconds: float = 5.0,
                        sample_hz: float = 0.0) -> dict:
        """One cluster-wide profile capture: per-process stack samples +
        guarded XLA traces + memory snapshots, plus the head's span
        timeline (merge with ray_tpu.profiling.merge)."""
        return self.head.call("profile_cluster", seconds=seconds,
                              sample_hz=sample_hz,
                              timeout=float(seconds) + 120.0)

    def stack_cluster(self) -> dict:
        """Immediate stack dump of every daemon/worker process."""
        return self.head.call("stack_cluster", timeout=60)

    def dump_worker_stack(self, worker_id: str) -> dict:
        """One worker's thread stacks, resolved through the head's worker
        directory (the `ray stack <worker>` verb)."""
        res = self.head.call("resolve_worker", worker_id=worker_id)
        addr = res.get("addr")
        if not addr:
            raise ValueError(f"unknown worker {worker_id!r}")
        return self._peer(tuple(addr)).call("dump_stack", timeout=10)

    def device_memory(self) -> dict:
        """Per-node device/host memory snapshots."""
        return self.head.call("device_memory", timeout=60)

    def train_stats(self) -> dict:
        """The head's straggler table (per-rank step-time summaries)."""
        return self.head.call("get_train_stats")

    def get_goodput(self, run: str | None = None) -> dict:
        """The head's goodput rollup: per-run/fleet goodput % with full
        badput breakdown in chip-seconds, plus serve request-goodput."""
        return self.head.call("get_goodput", run=run)

    # ------------------------------------------------------------ watchdog
    def incidents(self, since: float = 0.0, limit: int = 100,
                  incident_id: str | None = None) -> dict:
        """Health-watchdog incidents the head has assembled (bounded)."""
        return self.head.call("get_incidents", since=since, limit=limit,
                              incident_id=incident_id)

    def get_timeseries(self, name: str | None = None,
                       source: str | None = None,
                       node_id: str | None = None,
                       tags: dict | None = None,
                       since: float = 0.0, max_points: int = 0,
                       max_age_s: float = 0.0) -> dict:
        """The head's rolling hot-path series store (watchdog feed).
        ``max_age_s`` filters HEAD-side (skew-safe liveness window)."""
        return self.head.call("get_timeseries", name=name, source=source,
                              node_id=node_id, tags=tags, since=since,
                              max_points=max_points, max_age_s=max_age_s)

    def watchdog_status(self) -> dict:
        return self.head.call("watchdog_status")

    # ---------------------------------------------------------------- chaos
    def chaos_cluster(self, rules=None, clear: bool = False) -> dict:
        """Install/clear fault-injection rules fleet-wide (head -> every
        daemon -> every worker); also installs in THIS process so driver-
        side probes (e.g. its rpc.server) see the same schedule."""
        from ray_tpu.chaos import injector

        if clear:
            injector.clear()
        if rules:
            injector.install(rules, replace=False)
        return self.head.call("chaos", rules=rules, clear=clear, timeout=60)

    # ------------------------------------------------------------------ serving
    async def _handle_ping(self, conn, **kw):
        return {"ok": True, "worker_id": self.worker_id.hex()}

    async def _handle_dump_stack(self, conn, **kw):
        from ray_tpu.profiling.sampler import dump_stacks

        return {"worker_id": self.worker_id.hex(),
                "node_id": self.my_node_id, "pid": os.getpid(),
                "stacks": dump_stacks()}

    async def _handle_memory_snapshot(self, conn, **kw):
        from ray_tpu.profiling.memory import memory_snapshot

        snap = memory_snapshot()
        snap["worker_id"] = self.worker_id.hex()
        snap["node_id"] = self.my_node_id
        return snap

    def _handle_dag_chan_push(self, conn, msg):
        """Raw handler: compiled-graph direct-channel frame (data inline or
        a store-backed ref). Enqueue for the local reader; the reader acks
        after materializing (end-to-end channel backpressure)."""
        from ray_tpu.dag.direct import handle_chan_push

        handle_chan_push(conn, msg)

    async def _handle_chaos_install(self, conn, rules=None,
                                    clear: bool = False, **kw):
        from ray_tpu.chaos import injector

        if clear:
            injector.clear()
        if rules:
            injector.install(rules, replace=False)
        st = injector.status()
        st["worker_id"] = self.worker_id.hex()
        return st

    # Relay-distribution knobs (reference: push_manager bounds concurrent
    # chunk sends; here the owner bounds outstanding referrals per copy).
    RELAY_MIN_BYTES = 1 << 20
    RELAY_REFERRALS_PER_COPY = 2
    REFERRAL_TTL_S = 15.0

    def _pick_copies(self, object_id: ObjectID, primary: str,
                     exclude: str = "") -> list[str] | None:
        """Choose the serving copies for one puller: a FULL copy leads (the
        RPC fallback path needs a sealed object to chunk from) plus up to
        ``transfer_max_sources - 1`` extra full/partial copies for the
        multi-source range engine — partial copies serve their landed
        ranges cut-through. Each source carries a bounded number of
        outstanding referrals (per-source in-flight budget, the egress
        bound of reference push_manager.h); returns None when every copy is
        saturated — the puller backs off briefly, by which time in-flight
        pulls have joined as partial copies and the budget has grown."""
        now = time.monotonic()
        per_src = self._referrals.setdefault(object_id, {})
        for src in list(per_src):
            fresh = [t for t in per_src[src]
                     if now - t < self.REFERRAL_TTL_S]
            if fresh:
                per_src[src] = fresh
            else:
                del per_src[src]
        full = [primary] + [h for h in sorted(self._replicas.get(object_id, ()))
                            if h != primary and h != exclude]
        partial = [h for h in sorted(self._partials.get(object_id, ()))
                   if h not in full and h != exclude]

        def load(src: str) -> int:
            return len(per_src.get(src, ()))

        budget = self.RELAY_REFERRALS_PER_COPY
        open_full = [s for s in full if load(s) < budget]
        if not open_full:
            if not any(load(s) < budget for s in partial):
                return None  # everything saturated: puller backs off
            # Full copies are all at budget but partial relays have slack:
            # lead with the least-loaded full copy anyway — the range
            # engine spreads most bytes onto the partials.
            open_full = [min(full, key=load)]
        lead = min(open_full, key=load)
        k = max(1, get_config().transfer_max_sources)
        extras = sorted((s for s in full + partial
                         if s != lead and load(s) < budget), key=load)
        picked = [lead] + extras[:k - 1]
        counts = self.refer_counts.setdefault(object_id, {})
        for s in picked:
            per_src.setdefault(s, []).append(now)
            counts[s] = counts.get(s, 0) + 1
        grants = self._referral_grants.setdefault(object_id, deque())
        grants.append((now, picked))
        while grants and now - grants[0][0] >= self.REFERRAL_TTL_S:
            grants.popleft()  # stamps already TTL-pruned above
        return picked

    def _local_size(self, object_id: ObjectID) -> int | None:
        n = self.store.size(object_id)
        if n is None and self.shm is not None:
            n = self.shm.size(object_id.binary())
        return n

    async def _handle_get_object(self, conn, oid: str, timeout: float = 10.0,
                                 poll_s: float | None = None,
                                 requester: str = ""):
        """Long-poll object resolution. ``poll_s`` is the CALLER's budget —
        always shorter than its RPC timeout, so under load we answer
        'pending' (caller re-polls) instead of letting the RPC time out
        (which the borrower must treat as owner death)."""
        object_id = ObjectID.from_hex(oid)

        deadline = time.monotonic() + (poll_s if poll_s else timeout)
        while time.monotonic() < deadline:
            size = self._local_size(object_id)
            if size is not None:
                if size >= self.RELAY_MIN_BYTES:
                    # Never inline large objects: refer the puller to
                    # serving copies (possibly us) so it uses the bounded
                    # chunk / native-transfer path and joins the relay set.
                    if await self._same_host_requester(requester,
                                                      self.my_node_id):
                        # Same-host puller: it reads the arena directly
                        # (no egress) — bypass the referral budget.
                        counts = self.refer_counts.setdefault(object_id, {})
                        me = self.worker_id.hex()
                        counts[me] = counts.get(me, 0) + 1
                        return {"location": me, "locations": [me],
                                "size": size, "budgeted": False}
                    locs = self._pick_copies(object_id, self.worker_id.hex(),
                                             exclude=requester)
                    if locs is None:
                        await asyncio.sleep(0.05)
                        continue  # referral budget exhausted: brief backoff
                    return {"location": locs[0], "locations": locs,
                            "size": size}
                data = await asyncio.get_running_loop().run_in_executor(
                    None, self._local_blob, object_id
                )
                if data is not None:
                    return {"data": data}
            holder = self._locations.get(object_id)
            if holder is not None:
                known = self._location_sizes.get(object_id)
                if known is None or known < self.RELAY_MIN_BYTES:
                    # Small or unknown-size remote object: plain referral.
                    # Relay budgeting would stall here — its referral slots
                    # are only freed by report_holder, which pullers send
                    # for large cached copies alone.
                    return {"location": holder}
                holder_node = self._holder_nodes.get(holder)
                if holder_node and await self._same_host_requester(
                        requester, holder_node):
                    counts = self.refer_counts.setdefault(object_id, {})
                    counts[holder] = counts.get(holder, 0) + 1
                    return {"location": holder, "locations": [holder],
                            "size": known, "budgeted": False}
                locs = self._pick_copies(object_id, holder,
                                         exclude=requester)
                if locs is None:
                    await asyncio.sleep(0.05)
                    continue
                return {"location": locs[0], "locations": locs,
                        "size": known}
            await asyncio.sleep(0.01)
        return {"pending": True}

    async def _same_host_requester(self, requester: str,
                                   holder_node: str) -> bool:
        """True when the requesting worker's node shares a host (boot id)
        with the serving copy's node — its pull is a direct arena read
        with no egress, so the referral budget doesn't apply. Best-effort:
        any resolution failure returns False (budgeted path)."""
        if not requester or not holder_node or \
                not get_config().transfer_same_host_arena:
            return False
        try:
            node = self._holder_nodes.get(requester)
            if node is None:
                res = await self.head.aio.call("resolve_worker",
                                               worker_id=requester)
                node = res.get("node_id") or ""
                if node:
                    self._holder_nodes[requester] = node
            if not node:
                return False
            if node == holder_node:
                return True
            nodes = await self._nodes_cached()
            plane_a = (nodes.get(node) or {}).get("object_plane") or {}
            plane_b = (nodes.get(holder_node) or {}).get("object_plane") or {}
            boot_a, boot_b = plane_a.get("boot_id"), plane_b.get("boot_id")
            return bool(boot_a) and boot_a == boot_b
        except Exception:
            return False

    async def _handle_pin_object(self, conn, oid: str):
        """The owner promoted our cached copy to primary: exempt it from
        the borrow-cache TTL sweep so it stays servable until the owner
        frees the object."""
        object_id = ObjectID.from_hex(oid)
        with self._borrow_lock:
            if self._local_size(object_id) is None:
                return {"ok": True, "present": False}
            self._pinned_borrows.add(object_id)
            self._borrow_cache.pop(object_id, None)
        return {"ok": True, "present": True}

    def _free_referral_slot(self, object_id: ObjectID) -> None:
        """A referred pull finished (copy cached, served same-host, or
        failed): return the OLDEST outstanding grant, uncharging every
        source it stamped (the TTL sweep reclaims any the reporter never
        returns)."""
        per_src = self._referrals.get(object_id)
        grants = self._referral_grants.get(object_id)
        if grants:
            _, picked = grants.popleft()
            if per_src:
                for s in picked:
                    stamps = per_src.get(s)
                    if stamps:
                        stamps.pop(0)
                        if not stamps:
                            del per_src[s]
            return
        if not per_src:
            return
        oldest = min((s for s in per_src if per_src[s]),
                     key=lambda s: per_src[s][0], default=None)
        if oldest is not None:
            per_src[oldest].pop(0)
            if not per_src[oldest]:
                del per_src[oldest]

    async def _handle_report_holder(self, conn, oid: str, worker_id: str,
                                    remove: bool = False,
                                    partial: bool = False,
                                    done: bool = False):
        """Relay-set bookkeeping from pullers:
        - default: the puller cached a servable FULL copy — add it to the
          relay set and free one referral slot.
        - ``partial``: the puller STARTED a pull — its node serves landed
          ranges cut-through, so it already counts as a serving copy for
          the range engine.
        - ``remove``: drop the worker's (partial or full) entry — stale
          entries would send later pullers on failed-fetch detours.
        - ``done``: the referred pull finished WITHOUT producing a copy
          (same-host arena read, or a failed pull): free the slot that
          referral held so waiting pullers don't sit out the TTL."""
        object_id = ObjectID.from_hex(oid)
        if remove or done:
            if remove:
                for table in (self._replicas, self._partials):
                    entries = table.get(object_id)
                    if entries is not None:
                        entries.discard(worker_id)
            if done:
                self._free_referral_slot(object_id)
            return {"ok": True}
        if partial:
            # Never downgrade a full copy to partial (a stale in-flight
            # advert can arrive after the completion report).
            if worker_id not in self._replicas.get(object_id, ()):
                self._partials.setdefault(object_id, set()).add(worker_id)
            return {"ok": True}
        partials = self._partials.get(object_id)
        if partials is not None:
            partials.discard(worker_id)
        self._replicas.setdefault(object_id, set()).add(worker_id)
        self._free_referral_slot(object_id)
        return {"ok": True}

    async def _handle_get_object_chunk(self, conn, oid: str, offset: int,
                                       length: int):
        """One chunk of a large object (reference: object transfer rides
        gRPC chunks, object_manager.proto + ObjectBufferPool). offset=0
        additionally reports the total size so the puller can preallocate.
        Serves CUT-THROUGH against the shm sealed-range watermark: an
        object still landing on this node answers with whatever prefix of
        the range is already valid (possibly empty — the puller retries)
        instead of 'missing'."""
        object_id = ObjectID.from_hex(oid)

        def read():
            if self.shm is not None:
                try:
                    view, avail = self.shm.get_partial(object_id.binary())
                    try:
                        total = len(view)
                        end = min(offset + length, avail)
                        chunk = bytes(view[offset:end]) \
                            if end > offset else b""
                        return chunk, total
                    finally:
                        view.release()
                        self.shm.release(object_id.binary())
                except KeyError:
                    pass
            if self.store.contains(object_id):
                blob = self.store.get(object_id)
                return blob[offset:offset + length], len(blob)
            return None, 0

        data, total = await asyncio.get_running_loop().run_in_executor(
            None, read)
        if data is None:
            return {"missing": True}
        return {"data": data, "total": total}

    def _report_holder_async(self, owner_addr, ref: ObjectRef, *,
                             partial: bool = False,
                             remove: bool = False) -> None:
        """Fire-and-forget report_holder to the owner (in-flight advertise
        / retraction) — never blocks the pull it describes."""
        async def _send():
            try:
                peer = await self._apeer(tuple(owner_addr))
                await peer.call("report_holder", oid=ref.hex(),
                                worker_id=self.worker_id.hex(),
                                partial=partial, remove=remove, timeout=5)
            except Exception:
                pass

        try:
            self._io.loop.call_soon_threadsafe(lambda: spawn_task(_send()))
        except RuntimeError:
            pass  # loop shut down

    def _retract_holder(self, oid: ObjectID) -> None:
        """If we advertised ourselves as a relay holder, retract — the
        owner must not refer pullers to a copy we dropped. Best-effort,
        off-thread (GC paths call this)."""
        owner_hex = self._reported_holder.pop(oid, None)
        if owner_hex is None or self._shutdown:
            return

        async def _retract():
            try:
                addr = await self._aresolve_worker_addr(owner_hex)
                if addr is not None:
                    peer = await self._apeer(addr)
                    await peer.call("report_holder", oid=oid.hex(),
                                    worker_id=self.worker_id.hex(),
                                    remove=True, timeout=5)
            except Exception:
                pass

        try:
            self._io.loop.call_soon_threadsafe(lambda: spawn_task(_retract()))
        except RuntimeError:
            pass  # loop shut down

    async def _handle_free_object(self, conn, oid: str):
        # Owner-directed free: drop every local copy, including the node
        # arena's (the owner has decided the object is dead).
        object_id = ObjectID.from_hex(oid)
        self.store.delete(object_id)
        self._reported_holder.pop(object_id, None)  # owner is deleting: no
        # retract round-trip needed
        self._borrow_cache.pop(object_id, None)
        self._pinned_borrows.discard(object_id)
        if self.shm is not None:
            try:
                self.shm.delete(object_id.binary())
            except Exception:
                # Pinned by in-process readers / cut-through servers:
                # abort reclaims on the last release instead of leaking.
                try:
                    self.shm.abort(object_id.binary())
                except Exception:
                    pass
        return {"ok": True}

    async def _handle_report_location(self, conn, oid: str, holder: str,
                                      size: int | None = None):
        object_id = ObjectID.from_hex(oid)
        self._locations[object_id] = holder
        if size:
            self._location_sizes[object_id] = int(size)
        self._notify_waiters()
        return {"ok": True}

    async def _handle_report_lost(self, conn, oid: str,
                                  holder: str | None = None):
        """A borrower found our recorded holder unreachable: run owner-side
        lineage recovery (reference: owner-driven recovery on lost copies).
        When the failed holder was merely a relay replica, just drop it
        from the relay set — the primary is intact."""
        object_id = ObjectID.from_hex(oid)
        if holder:
            for table in (self._replicas, self._partials):
                entries = table.get(object_id)
                if entries is not None:
                    entries.discard(holder)
        if self._local_contains(object_id):
            return {"ok": True, "state": "present"}
        if holder and holder != self._locations.get(object_id) \
                and self._locations.get(object_id) is not None:
            return {"ok": True, "state": "present"}  # a replica died, not us
        # Primary gone — promote a surviving relay replica before resorting
        # to recompute: a live copy beats lineage reconstruction (and is
        # the only option for put() objects, which have no lineage). The
        # promoted copy is a borrow-cache entry the holder would sweep
        # after BORROW_CACHE_TTL_S without knowing it became load-bearing —
        # pin it there before answering "present" (a dangling promotion
        # permanently loses put() objects).
        reps = self._replicas.get(object_id)
        if reps:
            # Pin candidates CONCURRENTLY under one bounded budget: the
            # borrower's report_lost RPC allows ~10 s, and sequential 5 s
            # timeouts against two dead holders would overrun it (the
            # caller would see RpcError and re-issue report_lost while
            # this handler still runs).
            async def _try_pin(candidate: str) -> str:
                """'pinned' | 'dead' (no copy / holder gone — drop it) |
                'unknown' (timeout/stall — the copy may still exist)."""
                try:
                    addr = await self._aresolve_worker_addr(candidate)
                    if addr is None:
                        return "dead"  # head says the worker is gone
                    peer = await self._apeer(addr)
                    res = await peer.call("pin_object", oid=oid, timeout=4)
                    return "pinned" if res.get("present") else "dead"
                except Exception:
                    return "unknown"

            candidates = sorted(reps)
            tasks = {asyncio.ensure_future(_try_pin(c)): c
                     for c in candidates}
            pinned = None
            pending = set(tasks)
            deadline = asyncio.get_running_loop().time() + 6.0
            try:
                # First success wins IMMEDIATELY — one live holder must not
                # wait out a stalled one's 4 s timeout. Slower verdicts that
                # did arrive still prune head-confirmed-dead candidates.
                while pending and pinned is None:
                    remaining = deadline - asyncio.get_running_loop().time()
                    if remaining <= 0:
                        break
                    done, pending = await asyncio.wait(
                        pending, timeout=remaining,
                        return_when=asyncio.FIRST_COMPLETED)
                    if not done:
                        break  # overall budget exhausted
                    for t in done:
                        c = tasks[t]
                        verdict = (t.result() if t.exception() is None
                                   else "unknown")
                        if verdict == "dead":
                            reps.discard(c)
                        elif verdict == "pinned" and pinned is None:
                            pinned = c
            finally:
                for t in pending:
                    t.cancel()
            if pinned is not None:
                self._locations[object_id] = pinned
                return {"ok": True, "state": "present"}
            if reps:
                # Some holders were merely slow/unreachable-right-now: do
                # NOT forget them — a transient stall must not turn into
                # permanent loss of a put() object. The borrower retries
                # and the next report_lost re-attempts the pin; candidates
                # the head declares dead were dropped above, so the set
                # only shrinks and this terminates.
                return {"ok": True, "state": "recovering"}
        self._locations.pop(object_id, None)
        self._replicas.pop(object_id, None)
        ok = self._recover_object(object_id)
        return {"ok": ok, "state": "recovering" if ok else "lost"}

    async def _on_pub_batch(self, events: list):
        """Coalesced pubsub delivery: the head's batched fan-out ships one
        ``pub_batch`` notify carrying every event buffered for this
        subscriber in the window (head.publish)."""
        for ev in events or ():
            await self._on_pub(ev.get("channel"), ev.get("payload") or {})

    async def _on_pub(self, channel: str, payload: dict):
        if channel == "actor_events":
            aid = payload.get("actor_id")
            state = payload.get("state")
            self._actor_states[aid] = state
            if state == "ALIVE" and payload.get("addr"):
                self._actor_addr_cache[aid] = tuple(payload["addr"])
            elif state in ("DEAD", "RESTARTING"):
                self._actor_addr_cache.pop(aid, None)

    def _notify_waiters(self) -> None:
        with self._wait_cond:
            self._wait_cond.notify_all()

    # ------------------------------------------------------------------ peers
    def _peer(self, addr: tuple[str, int]) -> RpcClient:
        """Sync peer client — caller threads only (never the io loop)."""
        addr = tuple(addr)
        with self._peer_lock:
            cli = self._peer_clients.get(addr)
            if cli is None:
                cli = RpcClient(*addr)
                self._peer_clients[addr] = cli
            return cli

    async def _apeer(self, addr: tuple[str, int]) -> AsyncRpcClient:
        """Async peer client — io-loop side."""
        addr = tuple(addr)
        cli = self._apeers.get(addr)
        if cli is None or cli._closed:
            cli = AsyncRpcClient(*addr)
            await cli.connect()
            self._apeers[addr] = cli
        return cli

    def _resolve_worker_addr(self, worker_hex: str) -> tuple[str, int] | None:
        return self._resolve_worker(worker_hex)[0]

    async def _aresolve_worker_addr(self, worker_hex: str):
        res = await self.head.aio.call("resolve_worker", worker_id=worker_hex)
        return tuple(res["addr"]) if res.get("addr") else None

    def _resolve_worker(self, worker_hex: str) -> tuple[tuple | None, str]:
        """Worker directory lookup, cached ~5s: a stale hit costs one
        failed connect (failed pulls invalidate the entry, so the retry
        re-resolves through the head), a cold hit costs a head round trip
        per pull."""
        now = time.monotonic()
        hit = self._worker_dir_cache.get(worker_hex)
        if hit is not None and now - hit[0] < 5.0:
            return hit[1], hit[2]
        res = self.head.call("resolve_worker", worker_id=worker_hex)
        addr = tuple(res["addr"]) if res.get("addr") else None
        node = res.get("node_id") or ""
        self._worker_dir_cache[worker_hex] = (now, addr, node)
        if node:
            self._holder_nodes[worker_hex] = node
        return addr, node

    def _node_transfer_info(self, node_id: str) -> tuple | None:
        """Cached node_id -> (transfer_addr, object_plane) for alive nodes
        with a native data plane (5s TTL). object_plane carries the node's
        arena name + host boot id for same-host zero-copy reads.

        An UNKNOWN-id miss also refreshes (rate-limited to one head round
        trip per 0.5s): a node that joined after the last snapshot would
        otherwise be invisible to the native plane for a full TTL,
        silently detouring its pulls onto the RPC chunk path. Alive nodes
        WITHOUT a native plane are cached as explicit None entries so
        their pulls don't re-trigger the miss refresh at 2 Hz forever."""
        now = time.monotonic()
        cached = self._xfer_cache
        stale = cached is None or now - cached[0] > 5.0
        if not stale and node_id not in cached[1] and now - cached[0] > 0.5:
            stale = True
        if stale:
            try:
                nodes = self.head.call("list_nodes")
            except Exception:
                return None
            snapshot = {
                nid: ((tuple(info["transfer_addr"]),
                       info.get("object_plane"))
                      if info.get("transfer_addr") else None)
                for nid, info in nodes.items()
                if info.get("alive")}
            if node_id not in snapshot:
                # Queried id is GONE (dead/departed node behind stale
                # object locations): negative-cache it too, or every
                # retried pull re-triggers this refresh at 2 Hz until
                # the locations age out.
                snapshot[node_id] = None
            cached = self._xfer_cache = (now, snapshot)
        return cached[1].get(node_id)

    def _node_transfer_addr(self, node_id: str) -> tuple | None:
        info = self._node_transfer_info(node_id)
        return info[0] if info is not None else None

    # ------------------------------------------------------------------ put/get
    # Released borrowed copies stay servable this long (relay cache).
    BORROW_CACHE_TTL_S = 30.0
    BORROW_CACHE_MAX = 256

    def _release_object(self, oid: ObjectID, rec=None) -> None:
        # Borrowed copies OUTLIVE the borrow (plasma semantics: a pulled
        # object stays in the store until evicted or owner-freed, not
        # deleted the moment the borrower's local refcount drops) — that is
        # what makes a puller a useful relay holder beyond the lifetime of
        # its own task. Bounded: a TTL + count cap sweep deletes old
        # entries and retracts their relay adverts (no owner broadcast
        # exists to do it for us).
        owns = rec is None or rec.owner_id == self.worker_id
        store_had = False
        if owns:
            store_had = self.store.delete(oid)
        elif oid not in self._pinned_borrows:
            self._borrow_cache[oid] = time.monotonic()
        self._recovery_attempts.pop(oid, None)
        self._replicas.pop(oid, None)
        self._partials.pop(oid, None)
        self._location_sizes.pop(oid, None)
        self._referrals.pop(oid, None)
        self._referral_grants.pop(oid, None)
        self.refer_counts.pop(oid, None)
        self._sweep_borrow_cache()
        # Lineage GC: drop the retained spec once its last return is
        # released (reference: lineage released with the object refs).
        if rec is not None and rec.lineage_task is not None:
            entry = self._lineage.get(rec.lineage_task.hex())
            if entry is not None:
                entry[2] -= 1
                if entry[2] <= 0:
                    self._lineage.pop(rec.lineage_task.hex(), None)
                    self._lineage_bytes -= len(entry[1])
        # The shm arena is shared node-wide: only the object's owner may
        # delete from it — a borrower releasing its cache must not GC data
        # other processes still reference (reference: owner-driven GC,
        # reference_counter.h). Objects the PROCESS store held were never
        # in the arena (the two are exclusive destinations) — skip the
        # native lookup, which was pure overhead for every inline result.
        if rec is not None and rec.owner_id == self.worker_id \
                and not store_had and self.shm is not None:
            try:
                self.shm.delete(oid.binary())
            except Exception:
                # Pinned (zero-copy views / cut-through serving in flight):
                # abort frees on the last release, plasma-style.
                try:
                    self.shm.abort(oid.binary())
                except Exception:
                    pass

    def _sweep_borrow_cache(self) -> None:
        now = time.monotonic()
        expired = [o for o, t in self._borrow_cache.items()
                   if now - t > self.BORROW_CACHE_TTL_S]
        over = len(self._borrow_cache) - len(expired) - self.BORROW_CACHE_MAX
        if over > 0:
            exp = set(expired)
            by_age = sorted((t, o) for o, t in self._borrow_cache.items()
                            if o not in exp)
            expired.extend(o for _, o in by_age[:over])
        for o in expired:
            with self._borrow_lock:
                if o in self._pinned_borrows:
                    # Promoted to primary between list computation and
                    # delete (pin_object landed mid-sweep): the copy is
                    # load-bearing now.
                    self._borrow_cache.pop(o, None)
                    continue
                self._borrow_cache.pop(o, None)
                self.store.delete(o)
            self._retract_holder(o)

    def _store_blob(self, oid: ObjectID, blob, owner) -> None:
        """Large blobs land in the node shm arena (visible to every local
        process, zero-copy); small ones in the process-local store.
        ``blob`` may be bytes or a list of buffers (scatter write)."""
        parts = blob if isinstance(blob, list) else [blob]
        total = sum(len(p) for p in parts)
        if self.shm is not None and total >= self.SHM_THRESHOLD:
            try:
                self.shm.put_parts(oid.binary(), parts)
                self._notify_waiters()
                return
            except Exception:
                pass  # arena full and unspillable: fall back
        self.store.put(oid, b"".join(parts) if len(parts) > 1 else parts[0],
                       owner)

    def _local_blob(self, oid: ObjectID, as_view: bool = False):
        """Local blob; with as_view=True a shm hit returns a pinned
        ArenaView (zero-copy consumption in get()); peer-serving RPC
        paths keep bytes."""
        if self.store.contains(oid):
            return self.store.get(oid)
        if self.shm is not None:
            try:
                if as_view:
                    return self.shm.get_view(oid.binary())
                return self.shm.get_bytes(oid.binary())
            except KeyError:
                pass
        return None

    def _local_contains(self, oid: ObjectID) -> bool:
        if self.store.contains(oid):
            return True
        return self.shm is not None and self.shm.contains(oid.binary())

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.worker_id)
        self._store_blob(oid, serialization.serialize_parts(value),
                         self.worker_id)
        lr = 0 if refcounting_suppressed() else 1
        self.refs.add_owned(oid, self.worker_id, local_refs=lr)
        return (ObjectRef.counted if lr else ObjectRef)(oid, self.worker_id)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            data = self._fetch(ref, deadline)
            value = serialization.deserialize(data)
            if isinstance(value, (TaskError, ActorDiedError, TaskCancelledError,
                          OutOfMemoryError)):
                raise value
            out.append(value)
        return out

    def _fetch(self, ref: ObjectRef, deadline: float | None) -> bytes:
        # 1. local (process store, then node shm arena)
        local = self._local_blob(ref.id, as_view=True)
        if local is not None:
            return local
        owner_hex = ref.owner_id.hex() if ref.owner_id else None
        am_owner = ref.owner_id == self.worker_id
        holder_failures = 0
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            if am_owner:
                # Block on the store's seal event (inline results land there);
                # wake periodically to check for a large-result location report.
                holder = self._locations.get(ref.id)
                if holder is not None:
                    data = self._fetch_from_holder(holder, ref)
                    if data is not None:
                        return data
                    self._worker_dir_cache.pop(holder, None)  # re-resolve
                    holder_failures += 1
                    if holder_failures >= 2:
                        # Holder is gone: reconstruct from lineage by
                        # resubmitting the creating task (reference:
                        # object_recovery_manager.h:41), or fail for
                        # unrecoverable objects (puts, exhausted retries).
                        holder_failures = 0
                        self._locations.pop(ref.id, None)
                        if not self._recover_object(ref.id):
                            raise ObjectLostError(
                                ref.hex(),
                                "holder died and the object is not "
                                "reconstructable (no retained lineage, or "
                                "recovery retries exhausted)")
                    time.sleep(0.01)
                    continue
                step = 0.1 if remaining is None else min(0.1, remaining)
                try:
                    return self.store.get(ref.id, timeout=step)
                except TimeoutError:
                    # A local worker may have deposited the result in the
                    # node arena rather than our process store.
                    if self.shm is not None:
                        try:
                            return self.shm.get_bytes(ref.id.binary())
                        except KeyError:
                            pass
                    continue
            # borrower: ask the owner
            if owner_hex is None:
                raise ObjectLostError(ref.hex(), "ref has no owner")
            addr = self._resolve_worker_addr(owner_hex)
            if addr is None:
                raise ObjectLostError(ref.hex(), "owner not found (OwnerDied)")
            poll = min(remaining or 10.0, 10.0)
            try:
                res = self._peer(addr).call("get_object", oid=ref.hex(),
                                            poll_s=poll, timeout=poll + 5,
                                            requester=self.worker_id.hex())
            except TimeoutError:
                # Long-poll overran under load (TimeoutError is an OSError
                # subclass — it must NOT read as owner death); re-ask until
                # our own deadline expires.
                continue
            except (RpcError, OSError):
                raise ObjectLostError(ref.hex(), "owner unreachable")
            if res.get("data") is not None:
                self.store.put(ref.id, res["data"], ref.owner_id)
                return res["data"]
            if res.get("location"):
                locations = res.get("locations") or [res["location"]]
                size_hint = res.get("size") or 0
                # A budgeted locations list means the owner charged a
                # referral slot: exactly one report must hand it back
                # (full-copy report, or done=True otherwise).
                referred = res.get("locations") is not None \
                    and res.get("budgeted", True)
                # Cut-through advertise: tell the owner we are PULLING this
                # object before the bytes move — our node serves landed
                # ranges against the watermark, so later pullers can ride
                # us mid-transfer (reference: push_manager relay trees,
                # here started one hop earlier). Skipped when the copy is
                # same-host readable (no bytes will land here).
                advertise = (self.shm is not None and referred
                             and size_hint >= self.RELAY_MIN_BYTES
                             and not self._local_contains(ref.id))
                if advertise:
                    _, lead_node = self._resolve_worker(locations[0])
                    if lead_node and self._peer_arena_plane(lead_node):
                        advertise = False
                if advertise:
                    self._report_holder_async(addr, ref, partial=True)
                    self._reported_holder[ref.id] = owner_hex
                self._pull_extra[ref.id] = tuple(locations[1:])
                try:
                    data = self._fetch_from_holder(locations[0], ref)
                finally:
                    self._pull_extra.pop(ref.id, None)
                if data is not None:
                    # Relay distribution: if we cached a servable copy,
                    # tell the owner so later pullers can fetch from US
                    # instead of the source (reference: push_manager relay
                    # trees; bounded source egress).
                    if len(data) >= self.RELAY_MIN_BYTES and \
                            self._local_contains(ref.id):
                        try:
                            self._peer(addr).call(
                                "report_holder", oid=ref.hex(),
                                worker_id=self.worker_id.hex(), timeout=5)
                            self._reported_holder[ref.id] = owner_hex
                        except (RpcError, OSError):
                            pass
                    elif referred:
                        # Served without landing a local copy (same-host
                        # arena read / process-local cache): hand the
                        # referral slot back, retracting any stale
                        # in-flight advert with it.
                        self._report_holder_async(addr, ref, done=True,
                                                  remove=advertise)
                        self._reported_holder.pop(ref.id, None)
                    return data
                if referred:
                    # The pull failed: hand the slot back (and retract the
                    # in-flight advert before the owner refers anyone else
                    # to us).
                    self._report_holder_async(addr, ref, done=True,
                                              remove=advertise)
                    self._reported_holder.pop(ref.id, None)
                # The holder may have moved/died: drop its cached
                # directory row so the retry re-resolves through the head.
                self._worker_dir_cache.pop(locations[0], None)
                holder_failures += 1
                if holder_failures >= 2:
                    # Tell the owner its recorded holder is unreachable so
                    # IT can run recovery (only the owner has the lineage).
                    holder_failures = 0
                    try:
                        verdict = self._peer(addr).call(
                            "report_lost", oid=ref.hex(),
                            holder=res["location"], timeout=10)
                    except (RpcError, OSError):
                        verdict = None
                    if verdict is not None and verdict.get("state") == "lost":
                        raise ObjectLostError(
                            ref.hex(), "owner cannot reconstruct the object")
            # pending: loop

    # Node-to-node transfer chunking (reference: object_manager.proto moves
    # objects in chunks through ObjectBufferPool; PullManager bounds the
    # bytes in flight, pull_manager.h:50).
    PULL_CHUNK = 4 * 1024 * 1024
    PULL_WINDOW = 4  # concurrent chunk requests (bounded in-flight bytes)

    def _pull_sources(self, holder_node: str,
                      ref: ObjectRef) -> list[tuple]:
        """Transfer endpoints for a pull: the lead holder's node plus the
        extra serving copies the owner's referral handed out (full or
        partial — partial nodes serve their landed ranges cut-through),
        resolved to distinct node transfer addresses."""
        sources = []
        lead = self._node_transfer_addr(holder_node)
        if lead is not None:
            sources.append(tuple(lead))
        extra = self._pull_extra.get(ref.id, ())
        if extra:
            nodes = self._worker_nodes_for(extra)
            for whex in extra:
                node = nodes.get(whex)
                if not node or node == holder_node or node == self.my_node_id:
                    continue
                addr = self._node_transfer_addr(node)
                if addr is not None and tuple(addr) not in sources:
                    sources.append(tuple(addr))
        return sources

    def _worker_nodes_for(self, worker_hexes) -> dict[str, str]:
        """worker hex -> node hex, batch-resolved through the head's
        directory (one RPC for all unknown workers of a referral)."""
        missing = [w for w in worker_hexes if w not in self._holder_nodes]
        if missing:
            try:
                res = self.head.call("resolve_workers", worker_ids=missing,
                                     timeout=5)
                for whex, info in (res.get("workers") or {}).items():
                    if info and info.get("node_id"):
                        self._holder_nodes[whex] = info["node_id"]
            except Exception:
                pass  # unresolved workers just drop out of the source set
        return {w: self._holder_nodes.get(w, "") for w in worker_hexes}

    def _peer_arena_plane(self, holder_node: str) -> dict | None:
        """The holder node's object-plane descriptor when its arena is
        mappable from THIS process (same host boot id, distinct segment),
        else None."""
        if not get_config().transfer_same_host_arena:
            return None
        info = self._node_transfer_info(holder_node)
        if info is None or not info[1]:
            return None
        plane = info[1]
        name = plane.get("shm_name")
        from ray_tpu.core import transfer

        if not name or not transfer.host_boot_id() or \
                plane.get("boot_id") != transfer.host_boot_id():
            return None
        if self.shm is not None and self.shm.name.lstrip("/") == \
                name.lstrip("/"):
            return None  # our own arena: the regular local path covers it
        return plane

    def _peer_arena_view(self, holder_node: str, ref: ObjectRef):
        """Same-host zero-copy read: when the serving copy's arena lives on
        THIS host (boot ids match), map the peer node's segment and return
        a pinned view of the sealed object — no wire, no local copy
        (plasma-style same-host sharing extended across co-hosted node
        daemons; the shm store keeps all metadata in the segment, so the
        cross-process pin/refcount protocol works from any process on the
        host). Returns None when inapplicable — caller rides the transfer
        engine (which also covers the mid-pull cut-through case)."""
        plane = self._peer_arena_plane(holder_node)
        if plane is None:
            return None
        name = plane["shm_name"]
        peer = self._peer_arenas.get(name)
        if peer is None:
            try:
                from ray_tpu.core.shm_store import SharedMemoryStore

                peer = SharedMemoryStore(name, create=False)
            except Exception:
                return None  # segment gone (node died): transfer engine
            self._peer_arenas[name] = peer
        try:
            t0 = time.perf_counter()
            view = peer.get_view(ref.id.binary())
        except Exception:
            return None  # not sealed there (mid-pull) or evicted
        from ray_tpu.core.transfer import observe_transfer

        observe_transfer("arena_view", len(view), time.perf_counter() - t0)
        return view

    def _await_local_seal(self, ref: ObjectRef, timeout: float = 60.0):
        """Another local process is already pulling this object into the
        node arena: wait for its seal instead of moving the same bytes
        twice. Returns a pinned view, or None when the foreign pull
        aborted/stalled (caller pulls it itself / falls back)."""
        oid = ref.id.binary()
        deadline = time.monotonic() + timeout
        last_mark, last_advance = -1, time.monotonic()
        while time.monotonic() < deadline:
            if self.shm.contains(oid):
                return self.shm.get_view(oid)
            prog = self.shm.progress(oid)
            if prog is None:
                return None  # aborted: take over
            if prog[1] != last_mark:
                last_mark, last_advance = prog[1], time.monotonic()
            elif time.monotonic() - last_advance > 15.0:
                return None  # stalled foreign pull: fall back
            time.sleep(0.005)
        return None

    def _native_pull(self, holder_node: str, ref: ObjectRef) -> bytes | None:
        """Arena-to-arena pull over the native data plane (src/transfer/
        transfer.cc): zero Python in the byte path, ranges pipelined from
        every serving copy the referral named. Returns the bytes/view, or
        None to fall back to the RPC chunk path (object not in any source's
        arena, no transfer server, or any transport failure)."""
        if not holder_node:
            return None
        view = self._peer_arena_view(holder_node, ref)
        if view is not None:
            return view
        sources = self._pull_sources(holder_node, ref)
        if not sources:
            return None
        try:
            import contextlib

            from ray_tpu.core import transfer
            from ray_tpu.util import tracing

            oid = ref.id.binary()
            # Range-pull span only when a request trace is live on this
            # thread (a traced get() inside a serve/DAG request): the
            # cross-host KV or activation fetch shows up as a phase of
            # THAT request's waterfall. Untraced pulls pay nothing.
            span_cm = (tracing.span("transfer.pull", kind="client",
                                    attributes={"object": ref.id.hex()[:16],
                                                "sources": len(sources)})
                       if tracing.current_context() is not None
                       else contextlib.nullcontext())
            with span_cm as tspan:
                if self.shm is not None:
                    if self.shm.contains(oid):
                        return self.shm.get_view(oid)
                    try:
                        total = transfer.pull_to_store(self.shm.name, oid,
                                                       sources)
                    except transfer.ObjectInFlight:
                        # A same-node puller beat us to it: ride its
                        # transfer.
                        view = self._await_local_seal(ref)
                        if view is not None:
                            return view
                        # Foreign pull aborted: one fresh attempt of our
                        # own.
                        total = transfer.pull_to_store(self.shm.name, oid,
                                                       sources)
                    if total is None:
                        return None
                    if tspan is not None:
                        tspan.attributes["bytes"] = int(total)
                    # Sealing into the arena bypasses store.on_seal — wake
                    # concurrent wait()ers on this ref like the RPC path
                    # does.
                    self._notify_waiters()
                    # Pinned view, not bytes: get() deserializes straight
                    # out of the arena (large arrays zero-copy) instead of
                    # paying an arena->bytes traversal plus a deserialize
                    # copy.
                    return self.shm.get_view(oid)
                data = transfer.fetch_to_buffer(ref.id.binary(), sources)
                if data is not None:
                    if tspan is not None:
                        tspan.attributes["bytes"] = len(data)
                    # Cache like the RPC chunk path does, or every re-get
                    # of this ref re-transfers the whole object.
                    self.store.put(ref.id, data, ref.owner_id)
                    self._notify_waiters()
                return data
        except Exception:  # noqa: BLE001 - any native failure -> RPC path
            return None

    def _fetch_from_holder(self, holder_hex: str, ref: ObjectRef) -> bytes | None:
        from ray_tpu.core.transfer import observe_transfer

        addr, holder_node = self._resolve_worker(holder_hex)
        if addr is None:
            return None
        data = self._native_pull(holder_node, ref)
        if data is not None:
            return data
        t0 = time.perf_counter()
        try:  # dead holder: connect refused (ctor) or reset (call)
            peer = self._peer(addr)
            first = peer.call("get_object_chunk", oid=ref.hex(), offset=0,
                              length=self.PULL_CHUNK, timeout=30)
        except (RpcError, OSError):
            return None
        if first.get("missing"):
            return None
        total = first["total"]
        if total <= self.PULL_CHUNK and len(first["data"]) == total:
            # Cache single-chunk pulls like the multi-chunk path does —
            # an uncached borrow re-transfers on every get AND can never
            # join the relay set (report_holder requires a local copy).
            self.store.put(ref.id, first["data"], ref.owner_id)
            observe_transfer("rpc_chunk", total, time.perf_counter() - t0)
            return first["data"]
        data = self._pull_chunked(peer, ref, first["data"], total)
        if data is not None:
            observe_transfer("rpc_chunk", total, time.perf_counter() - t0)
        return data

    def _pull_chunked(self, peer: RpcClient, ref: ObjectRef,
                      first: bytes, total: int) -> bytes | None:
        """Assemble a large object from pipelined chunk pulls, writing each
        chunk straight into its destination (the node shm arena when it
        fits) — extra memory in flight is bounded by WINDOW × CHUNK. The
        holder may itself be mid-pull (cut-through): short/empty chunk
        replies are re-requested until the range lands. As contiguous
        chunks land HERE, the local watermark is published so this node
        relays the object before its own pull seals."""
        dest = None
        shm_backed = False
        if self.shm is not None:
            try:
                dest = self.shm.create(ref.id.binary(), total)
                shm_backed = True
            except Exception:
                dest = None
        if dest is None:
            dest = memoryview(bytearray(total))
        dest[:len(first)] = first
        oid_hex = ref.hex()
        chunk, window = self.PULL_CHUNK, self.PULL_WINDOW
        n_chunks = (total + chunk - 1) // chunk
        done = bytearray(n_chunks)
        contig = [0]  # chunks contiguously complete (loop-thread only)

        def mark_done(idx: int) -> None:
            done[idx] = 1
            advanced = False
            while contig[0] < n_chunks and done[contig[0]]:
                contig[0] += 1
                advanced = True
            if advanced and shm_backed:
                self.shm.set_progress(ref.id.binary(),
                                      min(contig[0] * chunk, total))

        async def pull():
            aio = peer.aio
            sem = asyncio.Semaphore(window)

            async def one(idx):
                end = min((idx + 1) * chunk, total)
                cur = idx * chunk + (len(first) if idx == 0 else 0)
                stalls = 0
                while cur < end:
                    async with sem:
                        r = await aio.call("get_object_chunk", oid=oid_hex,
                                           offset=cur, length=end - cur,
                                           timeout=60)
                    if r.get("missing"):
                        raise KeyError(oid_hex)
                    data = r["data"]
                    if data:
                        dest[cur:cur + len(data)] = data
                        cur += len(data)
                        stalls = 0
                    else:
                        # Holder's watermark hasn't reached this range yet.
                        stalls += 1
                        if stalls > 600:  # ~30 s without a byte: give up
                            raise TimeoutError(oid_hex)
                        await asyncio.sleep(0.05)
                mark_done(idx)

            tasks = [asyncio.ensure_future(one(idx))
                     for idx in range(1 if len(first) >= min(chunk, total)
                                      else 0, n_chunks)]
            if len(first) >= min(chunk, total):
                mark_done(0)
            try:
                await asyncio.gather(*tasks)
            except BaseException:
                # Cancel and AWAIT the siblings: an orphaned chunk coroutine
                # finishing later would write into arena memory the failure
                # path is about to free (use-after-free corruption).
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                raise

        try:
            self._io.run(pull())
        except Exception:
            if shm_backed:
                try:
                    # Abort, not delete: cut-through readers may already
                    # pin the partial entry (last release reclaims).
                    self.shm.abort(ref.id.binary())
                except Exception:
                    pass
            return None
        if shm_backed:
            self.shm.seal(ref.id.binary())
            self._notify_waiters()
            return self.shm.get_bytes(ref.id.binary())
        blob = bytes(dest)
        self.store.put(ref.id, blob, ref.owner_id)
        return blob

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready, pending = [], list(refs)
        while True:
            still = []
            for r in pending:
                if self._local_contains(r.id) or r.id in self._locations:
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            # Event-driven: woken by store seals / location reports. The
            # short cap covers cross-process shm arena seals, which have no
            # in-process notification.
            with self._wait_cond:
                self._wait_cond.wait(timeout=0.05)
        return ready, pending

    # ------------------------------------------------------------------ tasks
    def export_function(self, fn_id: str, fn_blob: bytes) -> None:
        """Publish a definition to the head registry once per process
        (reference: FunctionManager.export — definitions ride the GCS
        function table, not every TaskSpec). Idempotent: the head keeps
        the first copy of a content id; re-exports are cheap no-ops."""
        if fn_id in self._exported_fns:
            return
        self.head.call_retrying("fn_put", req_id=uuid.uuid4().hex,
                                fn_id=fn_id, blob=fn_blob)
        self._exported_fns.add(fn_id)
        observe_ctrl_fn("export", len(fn_blob))

    def fetch_function(self, fn_id: str, retries: int = 40) -> bytes:
        """Executor-side registry fetch with a negative-lookup retry: a
        definition exported through a different head connection can trail
        the first task naming it by a beat (head restart replay, racing
        exports). Bounded: a definition that never appears is an error on
        the task, not a hang."""
        for attempt in range(retries):
            res = self.head.call_retrying("fn_get", idempotent=True,
                                          timeout=10, fn_id=fn_id)
            blob = res.get("blob")
            if blob is not None:
                observe_ctrl_fn("fetch", len(blob))
                return blob
            time.sleep(0.05 * min(attempt + 1, 5))
        raise KeyError(f"function definition {fn_id} not in the registry")

    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        from ray_tpu.core.events import global_event_buffer

        return_ids = spec.return_ids()
        # Fused: ownership + the returned ref's local count in one
        # refcounter lock round trip (the per-ref __init__ accounting was a
        # top profile entry under multi-threaded submission). Suppressed
        # inside refcount_disabled() (proxy layers).
        lr = 0 if refcounting_suppressed() else 1
        for oid in return_ids:
            self.refs.add_owned(oid, self.worker_id, lineage_task=spec.task_id,
                                local_refs=lr)
        spec.owner_id = self.worker_id
        global_event_buffer().record(
            spec.task_id.hex(), spec.name, "SUBMITTED",
            worker_id=self.worker_id.hex(), job_id=spec.job_id.hex())
        item = _TaskItem(spec, serialization.dumps_spec(spec), return_ids)
        observe_ctrl_push("task", len(item.blob))
        if spec.num_returns != "streaming":
            # Retain lineage while any return is referenced so a lost copy
            # can be recomputed by resubmission — bounded by a byte budget
            # (reference: task_manager.h:184 max_lineage_bytes); evicted
            # entries just lose reconstructability, not correctness.
            self._lineage[spec.task_id.hex()] = [spec, item.blob,
                                                 len(return_ids)]
            self._lineage_bytes += len(item.blob)
            while self._lineage_bytes > self.MAX_LINEAGE_BYTES and \
                    len(self._lineage) > 1:
                old_tid, entry = next(iter(self._lineage.items()))
                if old_tid == spec.task_id.hex():
                    break
                self._lineage.pop(old_tid)
                self._lineage_bytes -= len(entry[1])
        # Coalesce cross-thread wakeups: call_soon_threadsafe writes the
        # loop's self-pipe per call (a syscall per task under fan-out
        # submission). One wakeup drains everything submitted since.
        with self._submit_lock:
            self._submit_buf.append(("task", item))
            wake = not self._submit_wake
            self._submit_wake = True
        if wake:
            self._io.loop.call_soon_threadsafe(self._drain_submits)
        make = ObjectRef.counted if lr else ObjectRef
        return [make(oid, self.worker_id) for oid in return_ids]

    def _drain_submits(self) -> None:
        # One wakeup drains every submission buffered since the last drain;
        # pumping AFTER the full drain is what batches a burst into one
        # push frame per worker (the old per-key deferred-pump tick bought
        # the same batching at one extra loop iteration per submit — pure
        # latency on the sync path).
        with self._submit_lock:
            items = list(self._submit_buf)
            self._submit_buf.clear()
            self._submit_wake = False
        touched_ks: dict[int, _KeyState] = {}
        touched_actors: dict[int, _ActorState] = {}
        for kind, item in items:
            if kind == "task":
                ks = self._enqueue_task(item)
                if ks is not None:
                    touched_ks[id(ks)] = ks
            else:
                st = self._enqueue_actor_task(item)
                if st is not None:
                    touched_actors[id(st)] = st
        for ks in touched_ks.values():
            self._pump(ks)
        for st in touched_actors.values():
            self._actor_pump(st)

    def _recover_object(self, object_id: ObjectID) -> bool:
        """Lineage reconstruction: resubmit the task that created the object
        (reference: ObjectRecoveryManager::RecoverObject). Returns False when
        the object has no recomputable lineage (puts, exhausted retries)."""
        # In-flight dedup FIRST (before the lineage lookup: a concurrent
        # lineage eviction mid-recovery must not turn a poll into a
        # spurious "cannot reconstruct"), and under a lock (a getter
        # thread and the IO loop's report_lost handler can race the
        # check-then-add — both resubmitting would run the task twice and
        # burn two attempts on one loss). Getters polling while the
        # resubmitted task runs report success without burning attempts.
        with self._recovery_lock:
            if object_id in self._recovering:
                return True
            tid = self.refs.lineage_task(object_id)
            if tid is None:
                return False
            entry = self._lineage.get(tid.hex())
            if entry is None:
                return False
            attempts = self._recovery_attempts.get(object_id, 0)
            if attempts >= 3:
                return False
            self._recovery_attempts[object_id] = attempts + 1
            self._recovering.add(object_id)
        spec, blob, _ = entry

        def on_loop():
            # _recovering stays set until the resubmitted task's results
            # land (_handle_task_reply / _store_error_local clear it).
            # Forget the stale location; the fresh execution reports anew.
            for oid in spec.return_ids():
                self._locations.pop(oid, None)
            item = _TaskItem(spec, blob, spec.return_ids())
            self._submit_on_loop(item)

        self._io.loop.call_soon_threadsafe(on_loop)
        return True

    # -- loop-side submission state machine --------------------------------
    def _enqueue_task(self, item: _TaskItem) -> _KeyState | None:
        """Queue one task on its key state WITHOUT pumping (the drain loop
        pumps each touched key once per wakeup — burst batching)."""
        tid = item.spec.task_id.hex()
        if tid in self._cancelled:
            self._store_error_local(item.return_ids, TaskCancelledError())
            return None
        key = item.spec.scheduling_key()
        ks = self._key_states.get(key)
        if ks is None:
            ks = _KeyState(key, dict(item.spec.resources), key[1],
                           strategy=item.spec.scheduling_strategy)
            self._key_states[key] = ks
        ks.queue.append(item)
        self._task_where[tid] = ("queued", ks)
        return ks

    def _submit_on_loop(self, item: _TaskItem) -> None:
        ks = self._enqueue_task(item)
        if ks is not None:
            self._pump(ks)

    def _pump(self, ks: _KeyState) -> None:
        if self._shutdown:
            return
        # A lease whose connection is already known-dead must not receive
        # dispatches: the push would fail AFTER hitting the socket buffer
        # (sent=True) and burn the task's retry budget for nothing.
        for w in list(ks.workers):
            if not w.dead and w.client._closed:
                w.dead = True
                ks.workers.remove(w)
                spawn_task(self._return_dead_lease(w))
        # Dispatch queued tasks onto workers with pipeline capacity. SPREAD
        # keys cap each worker at one in-flight task so the backlog forces
        # leases on other nodes (the round-robin entry point in
        # _lease_entry_daemon does the actual spreading).
        spread = ks.strategy is not None and ks.strategy.kind == "SPREAD"
        depth = 1 if spread else self.PIPELINE_DEPTH
        while ks.queue:
            live = [w for w in ks.workers
                    if not w.dead and w.inflight < depth]
            if spread and ks.pending_leases >= len(ks.queue):
                # Don't funnel the backlog through an already-used worker
                # while fresh leases (round-robined over other nodes) can
                # still absorb it — that would defeat the spread. But when
                # the backlog outruns the in-flight leases, reuse idle
                # leased workers instead of starving them behind lease
                # churn (which caps throughput below leased capacity).
                live = [w for w in live if w.served == 0]
            if not live:
                break
            w = min(live, key=lambda w: w.inflight)
            w.served += 1
            # Fill the worker's remaining pipeline capacity in ONE batched
            # push frame: per-task RPCs cost a frame + dispatch + executor
            # hop each, which dominates small-task throughput (reference
            # batches the lease-reuse path in normal_task_submitter.cc).
            batch: list[_TaskItem] = []
            room = 1 if spread else depth - w.inflight
            while ks.queue and len(batch) < room:
                item = ks.queue.popleft()
                tid = item.spec.task_id.hex()
                if tid in self._cancelled:
                    self._task_where.pop(tid, None)
                    self._store_error_local(item.return_ids,
                                            TaskCancelledError())
                    continue
                if item.spec.num_returns == "streaming" and batch:
                    # Streaming tasks ride the single-push path (their
                    # items flow back on the pushing connection).
                    ks.queue.appendleft(item)
                    break
                batch.append(item)
                if item.spec.num_returns == "streaming":
                    break
            if not batch:
                continue
            w.inflight += len(batch)
            for item in batch:
                self._task_where[item.spec.task_id.hex()] = ("running", w)
            # Streaming is the ONLY single-push user (its items flow back on
            # the pushing connection); everything else takes the batch path
            # even for one task, so there is a single failure-handling state
            # machine for normal tasks.
            if batch[0].spec.num_returns == "streaming":
                spawn_task(self._push_and_collect(ks, w, batch[0]))
            else:
                # Callback-style push (no per-batch coroutine): the reply
                # resolves a pending future whose done-callback lands the
                # results — two fewer loop iterations per round trip than
                # spawning an awaiting task.
                fut = w.client.call_nowait(
                    "push_task_batch", blobs=[i.blob for i in batch])
                fut.add_done_callback(
                    lambda f, w=w, batch=batch:
                    self._task_batch_done(ks, w, batch, f))
        # Scale out: request more leases while a backlog remains.
        if self._daemon is None:
            if ks.queue and not ks.workers and ks.pending_leases == 0:
                while ks.queue:
                    item = ks.queue.popleft()
                    self._task_where.pop(item.spec.task_id.hex(), None)
                    self._store_error_local(
                        item.return_ids,
                        TaskError(RuntimeError("no node daemon attached"),
                                  task_desc=item.spec.name))
            return
        capacity = sum(depth - w.inflight
                       for w in ks.workers if not w.dead)
        deficit = len(ks.queue) - capacity - ks.pending_leases
        if deficit <= 0 or ks.lease_rpcs >= self.MAX_PENDING_LEASES:
            return
        if spread:
            # SPREAD leases stay one-per-RPC: each request round-robins to
            # a DIFFERENT entry daemon (_lease_entry_daemon) — a batched
            # grant would land the whole backlog on one node.
            for _ in range(min(deficit,
                               self.MAX_PENDING_LEASES - ks.lease_rpcs)):
                ks.pending_leases += 1
                ks.lease_rpcs += 1
                spawn_task(self._request_lease(ks, 1))
        else:
            # One RPC sized by the queue deficit: the daemon grants up to
            # lease_batch_max workers in a single round trip (the per-RPC
            # pump was the multi-client fan-out bottleneck).
            count = min(deficit, get_config().lease_batch_max)
            ks.pending_leases += count
            ks.lease_rpcs += 1
            spawn_task(self._request_lease(ks, count))

    async def _push_and_collect(self, ks: _KeyState, w: _LeasedWorker,
                                item: _TaskItem) -> None:
        tid = item.spec.task_id.hex()
        try:
            reply = await w.client.call("push_task", spec_blob=item.blob,
                                        timeout=None)
            self._handle_task_reply(item.spec, item.return_ids, reply)
        except (RpcError, OSError) as e:
            # Worker failure: mark the lease dead, return it to the daemon
            # (a removed-but-unreturned lease permanently leaks the node's
            # resources), and retry (system retries — reference: max_retries
            # counts system failures). A request that never hit the wire
            # (cached lease whose worker was already gone) consumes no retry
            # budget — several stale leases must not exhaust a task's
            # retries before it ever runs.
            w.dead = True
            if w in ks.workers:
                ks.workers.remove(w)
                spawn_task(self._return_dead_lease(w))
            if getattr(e, "sent", True):
                item.attempts += 1
            if item.attempts > max(item.spec.max_retries, 0):
                err = await self._terminal_push_error(w, e, item.spec.name)
                self._store_error_local(item.return_ids, err)
            else:
                await asyncio.sleep(get_config().task_retry_delay_s)
                ks.queue.append(item)
                self._task_where[tid] = ("queued", ks)
        except Exception as e:  # noqa: BLE001
            self._store_error_local(item.return_ids,
                                    TaskError(e, task_desc=item.spec.name))
        finally:
            w.inflight -= 1
            if w.inflight <= 0:
                w.idle_since = time.monotonic()
            where = self._task_where.get(tid)
            if where is not None and where[0] == "running":
                self._task_where.pop(tid, None)
            self._pump(ks)

    def _task_batch_done(self, ks: _KeyState, w: _LeasedWorker,
                         items: list[_TaskItem], fut) -> None:
        """Completion callback of one batched push (one RPC carried N task
        specs, one reply carries N results, executed in order on the
        worker). Failure handling mirrors _push_and_collect, applied to
        every item of the batch; the slow terminal-error path (worker-fate
        RPC) runs as its own task off this callback."""
        try:
            try:
                if fut.cancelled():
                    raise RpcConnectionLost("push cancelled")
                exc = fut.exception()
                if exc is not None:
                    raise exc
                reply = fut.result()
                for item, r in zip(items, reply["replies"]):
                    self._handle_task_reply(item.spec, item.return_ids, r,
                                            notify=False)
                self._notify_waiters()
            except (RpcError, OSError) as e:
                w.dead = True
                if w in ks.workers:
                    ks.workers.remove(w)
                    spawn_task(self._return_dead_lease(w))
                sent = getattr(e, "sent", True)
                retry, terminal = [], []
                for item in items:
                    if sent:
                        item.attempts += 1
                    if item.attempts > max(item.spec.max_retries, 0):
                        terminal.append(item)
                    else:
                        retry.append(item)
                if terminal:
                    spawn_task(self._fail_items_terminal(w, e, terminal))
                if retry:
                    spawn_task(self._requeue_after_delay(ks, retry))
            except Exception as e:  # noqa: BLE001
                for item in items:
                    self._store_error_local(
                        item.return_ids, TaskError(e, task_desc=item.spec.name))
        finally:
            w.inflight -= len(items)
            if w.inflight <= 0:
                w.idle_since = time.monotonic()
            for item in items:
                tid = item.spec.task_id.hex()
                where = self._task_where.get(tid)
                if where is not None and where[0] == "running":
                    self._task_where.pop(tid, None)
            self._pump(ks)

    async def _fail_items_terminal(self, w: _LeasedWorker, e: Exception,
                                   items: list[_TaskItem]) -> None:
        for item in items:
            err = await self._terminal_push_error(w, e, item.spec.name)
            self._store_error_local(item.return_ids, err)

    async def _requeue_after_delay(self, ks: _KeyState,
                                   items: list[_TaskItem]) -> None:
        await asyncio.sleep(get_config().task_retry_delay_s)
        for item in items:
            ks.queue.append(item)
            self._task_where[item.spec.task_id.hex()] = ("queued", ks)
        self._pump(ks)

    async def _lease_entry_daemon(self, ks: _KeyState):
        """(daemon, pinned) the lease request starts at, per scheduling
        strategy (reference: scheduling policies in raylet/scheduling/policy/
        — hybrid pack/spread is the daemon's native spillback behavior):
        - DEFAULT: local daemon (hybrid: local until busy, then spill).
        - SPREAD: round-robin over feasible alive nodes (spread_scheduling
          _policy.h), unpinned so a busy pick still spills.
        - NODE_AFFINITY: the target node's daemon, pinned unless soft; a
          dead/unknown hard target fails the lease loudly.
        """
        strat = ks.strategy
        kind = getattr(strat, "kind", "DEFAULT")
        if kind == "SPREAD":
            try:
                nodes = await self.head.aio.call("list_nodes")
            except Exception:
                return self._daemon.aio, False
            feasible = sorted(
                (nid, tuple(info["addr"])) for nid, info in nodes.items()
                if info["alive"] and all(
                    info["resources"].get(k, 0.0) >= v
                    for k, v in ks.resources.items()))
            if feasible:
                nid, addr = feasible[ks.spread_idx % len(feasible)]
                ks.spread_idx += 1
                return (await self._apeer(addr)), False
            return self._daemon.aio, False
        if kind == "NODE_AFFINITY":
            nodes = await self.head.aio.call("list_nodes")
            info = nodes.get(strat.node_id_hex)
            if info is None or not info["alive"]:
                if strat.soft:
                    return self._daemon.aio, False
                raise ValueError(
                    f"node affinity target {strat.node_id_hex} is not alive")
            return (await self._apeer(tuple(info["addr"]))), not strat.soft
        # Data locality (reference: lease_policy.cc LocalityAwareLeasePolicy,
        # SURVEY §3.2 step 2): when the task at the front of the queue
        # consumes large objects held on a remote node, lease from that
        # node's daemon so the bytes don't cross the wire. Only non-inline
        # objects appear in _locations, so small args never redirect.
        if ks.queue:
            nid = await self._locality_node(ks.queue[0].spec)
            if nid is not None:
                try:
                    info = (await self._nodes_cached()).get(nid)
                    if info is not None and info["alive"] and all(
                            info["resources"].get(k, 0.0) >= v
                            for k, v in ks.resources.items()):
                        return (await self._apeer(tuple(info["addr"]))), False
                except Exception:
                    pass  # head hiccup: fall through to the local daemon
        return self._daemon.aio, False

    async def _refresh_daemon(self) -> bool:
        """A node-daemon connection died (daemon SIGKILLed/crashed):
        re-point self._daemon at a live daemon — our own node's if it came
        back, else any alive node's — so lease traffic keeps flowing
        (reference: raylet clients re-resolve through the GCS node table
        after a raylet death). Returns True when a live daemon answered."""
        if self._daemon is None:
            return False
        try:
            nodes = await self.head.aio.call("list_nodes", timeout=10)
        except Exception:
            return False
        candidates = sorted(
            ((nid, tuple(info["addr"])) for nid, info in nodes.items()
             if info.get("alive") and info.get("addr")),
            key=lambda kv: (kv[0] != self.my_node_id, kv[0]))
        for _nid, addr in candidates:
            fresh = AsyncRpcClient(*addr)
            try:
                await asyncio.wait_for(fresh.connect(), timeout=5)
            except Exception:
                continue  # head hasn't noticed this death yet: next node
            old = self._daemon._async
            self._daemon._async = fresh
            self.node_daemon_addr = addr
            try:
                await old.close()
            except Exception:
                pass
            return True
        return False

    async def _nodes_cached(self) -> dict:
        """TTL-cached head node view — the locality branch runs per lease
        request; an uncached list_nodes there would serialize lease
        throughput on head round-trips (same pattern as _xfer_cache)."""
        now = time.monotonic()
        if self._nodes_cache is not None and now - self._nodes_cache[0] < 1.0:
            return self._nodes_cache[1]
        nodes = await self.head.aio.call("list_nodes")
        self._nodes_cache = (now, nodes)
        return nodes

    async def _locality_node(self, spec) -> str | None:
        """Node holding the plurality of the task's located (large) args."""
        counts: dict[str, int] = {}
        for oid in spec.arg_ref_ids:
            holder = self._locations.get(oid)
            if holder is None:
                continue
            node = self._holder_nodes.get(holder)
            if node is None:
                try:
                    res = await self.head.aio.call("resolve_worker",
                                                   worker_id=holder)
                except Exception:
                    continue
                node = res.get("node_id") or ""
                self._holder_nodes[holder] = node
            if node:
                counts[node] = counts.get(node, 0) + 1
        if not counts:
            return None
        return max(counts.items(), key=lambda kv: kv[1])[0]

    async def _request_lease(self, ks: _KeyState, count: int = 1) -> None:
        """Lease up to ``count`` workers from the local daemon (or the
        strategy's entry node) in one RPC, following spillback redirects
        (reference: cluster_lease_manager spillback). Granted workers that
        refuse connections (killed between grant and connect) are returned
        and the lease re-requested."""
        from ray_tpu.util import tracing

        # One request id for the whole acquisition: a retry after the
        # daemon connection died mid-reply replays the SAME id, and the
        # daemon's lease dedup hands back the already-granted workers
        # instead of leaking them and granting fresh ones.
        req_id = uuid.uuid4().hex
        try:
            for _ in range(4):
                try:
                    daemon, pinned = await self._lease_entry_daemon(ks)
                    # Stage span for the control-plane breakdown
                    # (devbench/control_plane.py): grant latency = one
                    # daemon round trip, possibly plus spill hops.
                    with tracing.span("lease_grant",
                                      attributes={"count": count}):
                        res = await daemon.call(
                            "lease_workers", resources=ks.resources,
                            count=count, env_hash=ks.env_hash, timeout=None,
                            allow_spill=not pinned,
                            owner=self.worker_id.hex(), req_id=req_id)
                    hops = 0
                    while res.get("spill") and hops < 4:
                        daemon = await self._apeer(tuple(res["spill"]))
                        # Final hop commits to its node: prevents spill
                        # ping-pong when every node is briefly busy.
                        res = await daemon.call("lease_workers",
                                                resources=ks.resources,
                                                count=count,
                                                env_hash=ks.env_hash,
                                                timeout=None,
                                                allow_spill=hops < 3,
                                                owner=self.worker_id.hex(),
                                                req_id=req_id)
                        hops += 1
                except (RpcConnectionLost, OSError):
                    # The daemon died mid-lease (SIGKILL chaos): a
                    # retryable INFRASTRUCTURE event, not a task failure —
                    # re-resolve a live daemon and re-lease within this
                    # retry budget instead of surfacing TaskError.
                    await self._refresh_daemon()
                    await asyncio.sleep(0.2)
                    continue
                if res.get("spill"):
                    raise ValueError(
                        f"lease spill chain exhausted for {ks.resources}")
                if res.get("error"):
                    if res.get("timeout"):
                        raise LeaseTimeoutError(res["error"])
                    raise ValueError(res["error"])

                async def _adopt(g: dict):
                    client = AsyncRpcClient(*tuple(g["addr"]))
                    client.on_notify("stream_item", self._on_stream_item)
                    try:
                        await client.connect()
                    except OSError:
                        # Dead-on-arrival worker (chaos kill mid-grant):
                        # hand the lease back so the daemon reaps it.
                        try:
                            await daemon.call("return_lease",
                                              lease_id=g["lease_id"])
                        except Exception:
                            pass
                        return None
                    return _LeasedWorker(g["lease_id"], g["worker_id"],
                                         tuple(g["addr"]), client, daemon)

                adopted = await asyncio.gather(
                    *(_adopt(g) for g in res.get("grants") or []))
                live = [w for w in adopted if w is not None]
                if live:
                    ks.workers.extend(live)
                    return
                # Every grant DOA: these leases were RECEIVED (and just
                # returned) — the retry is a NEW request, so it needs a
                # fresh id or the daemon's dedup would faithfully replay
                # the same dead grants forever. The stable-id replay is
                # only for attempts whose REPLY was lost (the except
                # branch above keeps req_id across those).
                req_id = uuid.uuid4().hex
                await asyncio.sleep(0.1)  # every grant DOA: retry
            raise ValueError("granted workers repeatedly unreachable")
        except Exception as e:  # noqa: BLE001
            # A lease TIMEOUT is a stale-demand signal, not a task failure:
            # the request was sized for an earlier queue depth (e.g. a burst
            # that finished on fewer workers than requested). Failing a
            # queued task for it poisons whatever happens to be queued when
            # the 30 s timer fires. Just fall through to the finally-pump,
            # which re-requests leases sized to the CURRENT deficit.
            # Genuinely un-servable demands (infeasible resources, dead
            # affinity targets, unreachable workers) still fail a waiting
            # task, mirroring the per-task acquire semantics.
            if not isinstance(e, LeaseTimeoutError) and ks.queue \
                    and not ks.workers:
                item = ks.queue.popleft()
                self._task_where.pop(item.spec.task_id.hex(), None)
                self._store_error_local(item.return_ids,
                                        TaskError(e, task_desc=item.spec.name))
        finally:
            ks.pending_leases -= count
            ks.lease_rpcs -= 1
            self._pump(ks)

    def _handle_task_reply(self, spec, return_ids, reply: dict,
                           notify: bool = True):
        if "stream_count" in reply:
            # End of a streaming task: the item count seals the stream
            # (return_ids == [end marker oid] for streaming specs).
            self.store.put(return_ids[0],
                           serialization.serialize(int(reply["stream_count"])),
                           self.worker_id)
            self._notify_waiters()
            return
        results = reply.get("results", [])
        for oid, r in zip(return_ids, results):
            self._recovering.discard(oid)
            # Fresh loss bursts get a fresh retry budget once a recovery
            # (or first execution) lands.
            self._recovery_attempts.pop(oid, None)
            if r.get("data") is not None:
                self.store.put(oid, r["data"], self.worker_id)
            elif r.get("location"):
                self._locations[oid] = r["location"]
                if r.get("size"):
                    self._location_sizes[oid] = int(r["size"])
        if notify:
            self._notify_waiters()

    async def _on_stream_item(self, task_id: str, index: int,
                              data: bytes | None = None,
                              location: str | None = None,
                              size: int | None = None):
        """A streaming task yielded item ``index`` (notify frame from the
        executing worker — arrives before the final reply by TCP ordering)."""
        from ray_tpu.utils.ids import TaskID

        oid = ObjectID.for_task_return(TaskID.from_hex(task_id), index)
        self.refs.add_owned(oid, self.worker_id)
        if data is not None:
            self.store.put(oid, data, self.worker_id)
        elif location:
            self._locations[oid] = location
            if size:
                self._location_sizes[oid] = int(size)
        self._notify_waiters()

    def _store_error_local(self, return_ids, err):
        blob = serialization.serialize(err)
        for oid in return_ids:
            self._recovering.discard(oid)
            self.store.put(oid, blob, self.worker_id)
        self._notify_waiters()

    async def _worker_kill_fate(self, w: _LeasedWorker) -> dict:
        """Why did the daemon kill this worker (empty if it just died)?
        Turns a dropped worker connection into a typed error — e.g. the
        memory monitor's OOM kill (reference: the raylet attaches a
        death-cause to task failures, node_manager.cc)."""
        try:
            return (await w.daemon.call(
                "worker_fate", worker_id=w.worker_id)) or {}
        except Exception:
            return {}

    @staticmethod
    def _oom_error(fate: dict, task_desc: str) -> OutOfMemoryError:
        return OutOfMemoryError(
            f"task {task_desc} was killed by the node memory monitor on "
            f"node {fate.get('node_id', '?')}: worker rss "
            f"{fate.get('rss', 0)} bytes, node worker usage "
            f"{fate.get('usage', 0)} of limit {fate.get('limit', 0)} bytes")

    async def _terminal_push_error(self, w: _LeasedWorker, e: Exception,
                                   task_desc: str):
        """Error for a task whose system-retry budget is exhausted: a
        typed OutOfMemoryError when the daemon killed the worker for
        memory, else a generic system-failure TaskError. The fate RPC is
        only paid here, not on retried failures."""
        from ray_tpu.core import flight_recorder

        fate = await self._worker_kill_fate(w)
        flight_recorder.record(
            "worker_failure", reason=f"{type(e).__name__}: {e}",
            node_id=self.my_node_id,
            extra={"worker_id": w.worker_id, "task": task_desc,
                   "fate": fate})
        if fate.get("oom"):
            return self._oom_error(fate, task_desc)
        return TaskError(RuntimeError(f"system failure: {e}"),
                         task_desc=task_desc)

    async def _return_dead_lease(self, w: _LeasedWorker) -> None:
        try:
            await w.daemon.call("return_lease", lease_id=w.lease_id)
        except Exception:
            pass  # daemon gone too; its own reaper frees the resources
        try:
            await w.client.close()
        except Exception:
            pass

    async def _lease_reaper(self):
        """Return idle leases after the keepalive window so other scheduling
        keys / clients aren't starved (reference: ReturnWorkerLease on idle)."""
        while not self._shutdown:
            keepalive = get_config().lease_keepalive_s
            await asyncio.sleep(keepalive / 2)
            now = time.monotonic()
            for ks in list(self._key_states.values()):
                for w in list(ks.workers):
                    if w.dead or (w.inflight <= 0
                                  and now - w.idle_since > keepalive):
                        ks.workers.remove(w)
                        try:
                            await w.daemon.call("return_lease",
                                                lease_id=w.lease_id)
                        except Exception:
                            pass
                        try:
                            await w.client.close()
                        except Exception:
                            pass
                if not ks.workers and not ks.queue and not ks.pending_leases:
                    self._key_states.pop(ks.key, None)

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        """Best-effort task cancellation (reference: CoreWorker::CancelTask —
        queued tasks are dropped; a running task is interrupted in the worker
        via an async-raised TaskCancelledError)."""
        tid = self.refs.lineage_task(ref.id)
        tid_hex = tid.hex() if tid is not None else None

        def on_loop():
            if tid_hex is None:
                self._store_error_local([ref.id], TaskCancelledError())
                return
            self._cancelled.add(tid_hex)
            where = self._task_where.pop(tid_hex, None)
            if where is not None:
                kind, target = where
                if kind == "queued":
                    ks = target
                    for item in list(ks.queue):
                        if item.spec.task_id.hex() == tid_hex:
                            ks.queue.remove(item)
                            self._store_error_local(item.return_ids,
                                                    TaskCancelledError())
                            break
                else:  # running on a leased worker
                    w: _LeasedWorker = target
                    spawn_task(w.client.call("cancel_task", task_id=tid_hex,
                                             force=force, timeout=5))
                return
            # Actor task: drop it from the per-actor queue if not yet sent
            # (reference: a dispatched actor method isn't interrupted unless
            # force — the real result lands if cancel loses the race).
            for st in self._actor_sm.values():
                for item in list(st.pending):
                    if item.spec.task_id.hex() == tid_hex:
                        st.pending.remove(item)
                        self._store_error_local(item.return_ids,
                                                TaskCancelledError())
                        return

        self._io.loop.call_soon_threadsafe(on_loop)

    # ------------------------------------------------------------------ actors
    def create_actor(self, spec: ActorCreationSpec) -> None:
        from ray_tpu.runtime_env.container import canonical_env_json

        spec.owner_id = self.worker_id
        strategy = spec.scheduling_strategy
        # Retrying + req-id-stamped: a head crash between applying the
        # registration and ACKing it (or a restart mid-call) answers the
        # retry from the WAL-replayed dedup table — exactly-once, never
        # "name taken" against our own first attempt.
        res = self.head.call_retrying(
            "register_actor", req_id=uuid.uuid4().hex,
            actor_id=spec.actor_id.hex(),
            spec_blob=cloudpickle.dumps(spec),
            resources=spec.resources,
            name=spec.name,
            namespace=spec.namespace,
            max_restarts=spec.max_restarts,
            lifetime=spec.lifetime,
            node_affinity=strategy.node_id_hex if strategy.kind == "NODE_AFFINITY" else None,
            affinity_soft=strategy.soft,
            env_json=canonical_env_json(getattr(spec, "runtime_env", None)),
        )
        if not res.get("ok"):
            raise ValueError(res.get("error", "actor registration failed"))

    async def _actor_info(self, aid: str) -> dict | None:
        return await self.head.aio.call("get_actor_info", actor_id=aid)

    def submit_actor_task(self, spec: TaskSpec) -> list[ObjectRef]:
        return_ids = spec.return_ids()
        lr = 0 if refcounting_suppressed() else 1
        for oid in return_ids:
            self.refs.add_owned(oid, self.worker_id, lineage_task=spec.task_id,
                                local_refs=lr)
        spec.owner_id = self.worker_id
        item = _TaskItem(spec, serialization.dumps_spec(spec), return_ids)
        observe_ctrl_push("actor", len(item.blob))
        with self._submit_lock:
            self._submit_buf.append(("actor", item))
            wake = not self._submit_wake
            self._submit_wake = True
        if wake:
            self._io.loop.call_soon_threadsafe(self._drain_submits)
        make = ObjectRef.counted if lr else ObjectRef
        return [make(oid, self.worker_id) for oid in return_ids]

    # -- loop-side actor state machine --------------------------------------
    def _enqueue_actor_task(self, item: _TaskItem) -> _ActorState:
        """Queue one call on its actor state WITHOUT pumping (the drain
        loop pumps each touched actor once per wakeup — burst batching)."""
        aid = item.spec.actor_id.hex()
        st = self._actor_sm.get(aid)
        if st is None:
            st = _ActorState(aid)
            self._actor_sm[aid] = st
        st.pending.append(item)
        return st

    def _actor_pump(self, st: _ActorState) -> None:
        if self._shutdown:
            return
        if st.client is None:
            if not st.resolving:
                st.resolving = True
                spawn_task(self._actor_resolve(st))
            return
        # FIFO dispatch: frames hit the wire in program order (reference:
        # sequence-numbered sends) over one connection, so the actor's
        # mailbox receives calls in order. Each call is its own correlated
        # request (call_nowait + done-callback — no task or batch gather
        # per call): replies resolve the right future in WHATEVER order
        # the actor finishes them, so a slow async call never blocks the
        # results of later calls (reference: direct actor call replies
        # correlate per-call in core_worker.cc).
        client = st.client
        while st.pending and st.inflight < st.window:
            if st.pending[0].spec.num_returns == "streaming":
                # Streaming rides the legacy push path (its items flow back
                # as notify frames on the pushing connection). The frame is
                # WRITTEN here, synchronously, so it keeps its place in
                # program order relative to the fast-path frames below (a
                # spawned-task send would let later calls overtake it).
                item = st.pending.popleft()
                st.inflight += 1
                fut = client.call_nowait("push_actor_task",
                                         spec_blob=item.blob)
                spawn_task(self._actor_push(st, client, item, fut))
                continue
            # Burst coalescing: one multi-call frame carries every call
            # queued this pump (up to 64), each with its own reply future.
            batch: list[_TaskItem] = []
            room = min(st.window - st.inflight, 64)
            while st.pending and len(batch) < room and \
                    st.pending[0].spec.num_returns != "streaming":
                batch.append(st.pending.popleft())
            st.inflight += len(batch)
            futs = client.call_many("push_actor_calls",
                                    [i.blob for i in batch])
            for item, fut in zip(batch, futs):
                fut.add_done_callback(
                    lambda f, item=item, client=client:
                    self._actor_call_done(st, client, item, f))

    async def _actor_resolve(self, st: _ActorState) -> None:
        """Wait for the actor to be ALIVE and open its connection. Transient
        head errors retry within the loop — only a DEAD verdict or the
        deadline fails the pending queue."""
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                addr = self._actor_addr_cache.get(st.actor_id)
                if addr is None:
                    try:
                        info = await self._actor_info(st.actor_id)
                    except Exception:  # head briefly unreachable: retry
                        await asyncio.sleep(0.1)
                        continue
                    if info is None:
                        raise ActorDiedError(st.actor_id, "unknown actor")
                    if info["state"] == "DEAD":
                        raise ActorDiedError(st.actor_id, info.get("reason", ""))
                    if info["state"] == "ALIVE" and info.get("addr"):
                        addr = tuple(info["addr"])
                        self._actor_addr_cache[st.actor_id] = addr
                if addr is not None:
                    client = AsyncRpcClient(*addr)
                    client.on_notify("stream_item", self._on_stream_item)
                    try:
                        await client.connect()
                    except OSError:
                        # Stale address (old incarnation): drop and re-ask.
                        self._actor_addr_cache.pop(st.actor_id, None)
                        await asyncio.sleep(0.05)
                        continue
                    st.addr = addr
                    st.client = client
                    return
                await asyncio.sleep(0.02)
            raise ActorDiedError(st.actor_id,
                                 "timed out waiting for actor to start")
        except ActorDiedError as e:
            self._fail_actor_queue(st, e)
        finally:
            st.resolving = False
            if st.client is not None:
                self._actor_pump(st)

    def _fail_actor_queue(self, st: _ActorState, err: ActorDiedError) -> None:
        from ray_tpu.core import flight_recorder

        if "killed via kill()" not in (err.reason or ""):
            flight_recorder.record("actor_death", reason=err.reason,
                                   actor_id=st.actor_id,
                                   node_id=self.my_node_id)
        for item in st.retrying:
            self._store_error_local(item.return_ids, err)
        st.retrying = []
        # Pending calls never hit the wire: flagged never_sent so callers
        # (serve's router) may re-route them without double-execution risk.
        unsent = ActorDiedError(err.actor_id_hex, err.reason,
                                never_sent=True)
        while st.pending:
            item = st.pending.popleft()
            self._store_error_local(item.return_ids, unsent)

    async def _actor_push(self, st: _ActorState, client: AsyncRpcClient,
                          item: _TaskItem, fut) -> None:
        """Await one already-sent legacy push (streaming calls; the frame
        was written in _actor_pump to preserve program order)."""
        try:
            reply = await fut
            if reply.get("dead"):
                raise RpcError(reply.get("reason", "actor dead"))
            self._handle_task_reply(item.spec, item.return_ids, reply)
        except (RpcError, OSError):
            # Connection lost / incarnation died. Only tear down st.client if
            # it is still the connection we used — a sibling failure may have
            # already installed a fresh one that must survive.
            if st.client is client:
                try:
                    await client.close()
                except Exception:
                    pass
                st.client = None
                self._actor_addr_cache.pop(st.actor_id, None)
            item.attempts += 1
            if item.attempts > 60:
                self._store_error_local(
                    item.return_ids,
                    ActorDiedError(st.actor_id, "worker connection lost"))
            elif st.client is not None:
                # A sibling already recovered onto a NEW incarnation: this
                # call was sent to the dead one and may have executed
                # there — at-most-once, it must fail, not replay.
                self._store_error_local(
                    item.return_ids, ActorDiedError(
                        st.actor_id, _SENT_CALL_LOST))
            else:
                st.retrying.append(item)
                if not st.recovering:
                    st.recovering = True
                    spawn_task(self._actor_recover(st, st.addr))
        except Exception as e:  # noqa: BLE001
            self._store_error_local(item.return_ids,
                                    TaskError(e, task_desc=item.spec.name))
        finally:
            st.inflight -= 1
            self._actor_pump(st)

    def _actor_call_done(self, st: _ActorState, client: AsyncRpcClient,
                         item: _TaskItem, fut) -> None:
        """Completion callback of one fast-path actor call (loop thread).
        Failure handling mirrors _actor_push: connection loss tears down
        the client once; failed items gather in ``retrying`` while recovery
        runs and FAIL with ActorDiedError once the incarnation is known to
        have changed (at-most-once — the call may have executed on the dead
        incarnation)."""
        try:
            try:
                if fut.cancelled():
                    raise RpcConnectionLost("call cancelled")
                exc = fut.exception()
                if exc is not None:
                    raise exc
                reply = fut.result()
                if reply.get("dead"):
                    raise RpcError(reply.get("reason", "actor dead"))
                self._handle_task_reply(item.spec, item.return_ids, reply)
                return
            except (RpcError, OSError):
                # Connection lost / incarnation died. Only tear down
                # st.client if it is still the connection we used — a
                # sibling failure may have already installed a fresh one
                # that must survive.
                if st.client is client:
                    spawn_task(client.close())
                    st.client = None
                    self._actor_addr_cache.pop(st.actor_id, None)
                item.attempts += 1
                if item.attempts > 60:
                    self._store_error_local(
                        item.return_ids,
                        ActorDiedError(st.actor_id, "worker connection lost"))
                elif st.client is not None:
                    # A sibling already recovered onto a NEW incarnation:
                    # this call was sent to the dead one and may have
                    # executed there — at-most-once, it must fail here.
                    self._store_error_local(
                        item.return_ids, ActorDiedError(
                            st.actor_id, _SENT_CALL_LOST))
                else:
                    st.retrying.append(item)
                    if not st.recovering:
                        st.recovering = True
                        spawn_task(self._actor_recover(st, st.addr))
            except Exception as e:  # noqa: BLE001
                self._store_error_local(
                    item.return_ids, TaskError(e, task_desc=item.spec.name))
        finally:
            st.inflight -= 1
            self._actor_pump(st)

    async def _actor_recover(self, st: _ActorState, old_addr) -> None:
        """Wait for a new incarnation. Calls that were already SENT to the
        dead incarnation (``st.retrying``) fail with ActorDiedError — they
        may have executed before the crash, and replaying a side-effectful
        call into the restarted actor breaks at-most-once semantics
        (observed as a crash-inducing call killing every incarnation in
        turn once failure detection got fast). Queued-but-never-sent calls
        (``st.pending``) flow to the new incarnation (reference:
        actor_task_submitter resubmits only tasks the dead incarnation
        never received; in-flight ones fail under max_task_retries=0)."""
        aid = st.actor_id
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                try:
                    info = await self._actor_info(aid)
                except Exception:
                    info = None
                state = (info or {}).get("state")
                if state == "DEAD":
                    raise ActorDiedError(aid, (info or {}).get(
                        "reason", "worker connection lost"))
                if state == "ALIVE" and info.get("addr") and \
                        tuple(info["addr"]) != (old_addr or ()):
                    self._actor_addr_cache[aid] = tuple(info["addr"])
                    break
                await asyncio.sleep(0.1)
            else:
                raise ActorDiedError(aid, "worker connection lost")
            for item in st.retrying:
                self._store_error_local(
                    item.return_ids,
                    ActorDiedError(aid, _SENT_CALL_LOST))
            st.retrying = []
            st.recovering = False
            self._actor_pump(st)
        except ActorDiedError as e:
            st.recovering = False
            self._fail_actor_queue(st, e)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.head.call_retrying("kill_actor", idempotent=True,
                                actor_id=actor_id.hex(),
                                no_restart=no_restart)

    def get_named_actor(self, name: str, namespace: str = "default") -> ActorID | None:
        res = self.head.call_retrying("get_named_actor", idempotent=True,
                                      name=name, namespace=namespace)
        return ActorID.from_hex(res["actor_id"]) if res.get("actor_id") else None

    def actor_is_alive(self, actor_id: ActorID) -> bool:
        info = self.head.call_retrying("get_actor_info", idempotent=True,
                                       actor_id=actor_id.hex())
        return bool(info and info["state"] == "ALIVE")

    # ------------------------------------------------------------------ placement groups
    def create_placement_group(self, pg_id, bundles, strategy, name=None,
                               labels=None) -> str | None:
        res = self.head.call_retrying(
            "create_placement_group", req_id=uuid.uuid4().hex,
            pg_id=pg_id.hex(), bundles=bundles, strategy=strategy, name=name)
        # The head inlines the first placement attempt: CREATED here lets
        # ready() skip its first state poll entirely.
        return (res or {}).get("state")

    def remove_placement_group(self, pg_id) -> None:
        self.head.call_retrying("remove_placement_group", idempotent=True,
                                pg_id=pg_id.hex())

    def placement_group_state(self, pg_id) -> str:
        return self.head.call_retrying("placement_group_state",
                                       idempotent=True,
                                       pg_id=pg_id.hex())["state"]

    # ------------------------------------------------------------------ KV
    def kv_put(self, key: str, value: bytes, ns: str = "default",
               overwrite: bool = True) -> bool:
        return bool(self.head.call_retrying(
            "kv_put", req_id=uuid.uuid4().hex, ns=ns, key=key, value=value,
            overwrite=overwrite).get("ok"))

    def kv_get(self, key: str, ns: str = "default") -> bytes | None:
        return self.head.call_retrying("kv_get", idempotent=True,
                                       ns=ns, key=key).get("value")

    def kv_del(self, key: str, ns: str = "default") -> None:
        self.head.call_retrying("kv_del", req_id=uuid.uuid4().hex,
                                ns=ns, key=key)

    def kv_keys(self, prefix: str = "", ns: str = "default") -> list[str]:
        return self.head.call_retrying("kv_keys", idempotent=True,
                                       ns=ns, prefix=prefix)["keys"]

    # ------------------------------------------------------------------ misc
    def head_status(self) -> dict:
        """Control-plane session facts (incarnation, uptime, restart
        count, reconcile/fence odometers) for `ray_tpu status`."""
        return self.head.call_retrying("head_status", idempotent=True)

    def head_rpc_counts(self) -> dict:
        """Per-method inbound frame counts at the head (control-plane RPC
        attribution; diff two snapshots around a workload)."""
        return self.head.call_retrying("rpc_counts", idempotent=True)

    def state_snapshot(self, parts: list | None = None) -> dict:
        """``parts`` names the head tables to fetch (["nodes"], ["actors"],
        ...) so a single-entity state-API listing stops shipping the whole
        cluster dump; None keeps the full snapshot."""
        snap = self.head.call_retrying("state_snapshot", idempotent=True,
                                       parts=parts)
        if parts is None or "objects" in parts:
            snap["objects"] = self.store.stats()
        return snap

    def node_summary(self) -> dict:
        """O(1)-payload node aggregate (count/alive/resource totals) —
        the fleet-size-safe alternative to a full list_nodes."""
        return self.head.call_retrying(
            "list_nodes", idempotent=True, summary=True)["summary"]

    def task_events(self, since: int = 0, epoch: str = "") -> dict:
        """Cluster-wide task events newer than the ``since`` cursor."""
        return self.head.call("get_task_events", since=since, epoch=epoch)

    def cluster_resources(self) -> dict[str, float]:
        return self.head.call_retrying("cluster_resources", idempotent=True)

    def available_resources(self) -> dict[str, float]:
        return self.head.call_retrying("available_resources",
                                       idempotent=True)

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        self._stop_flush.set()
        try:
            self._reaper_task.cancel()
        except Exception:
            pass
        try:
            self._io.run(self.server.stop())
        except Exception:
            pass
        for cli in list(self._peer_clients.values()):
            cli.close()
        self.head.close()
        if self._daemon:
            self._daemon.close()
