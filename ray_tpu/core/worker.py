"""Per-process worker singleton: the façade all API calls go through.

Capability parity with the reference's core worker façade (reference:
python/ray/_private/worker.py:443 ``class Worker`` wrapping the Cython
CoreWorker, _raylet.pyx:2779): holds the connection to the runtime (local
in-process engine or the distributed cluster client), the job/worker identity,
and the task-context stack used by ``get_runtime_context``.
"""

from __future__ import annotations

import threading
from typing import Any

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.utils.ids import ActorID, JobID, NodeID, TaskID, WorkerID


class RuntimeContext:
    """What `get_runtime_context()` exposes inside tasks/actors."""

    def __init__(self, worker: "Worker"):
        self._worker = worker

    @property
    def job_id(self) -> JobID:
        return self._worker.job_id

    @property
    def node_id(self) -> NodeID:
        return self._worker.node_id

    @property
    def worker_id(self) -> WorkerID:
        return self._worker.worker_id

    def get_actor_id(self) -> str | None:
        aid = getattr(_task_context, "actor_id", None)
        return aid.hex() if aid else None

    def get_task_id(self) -> str | None:
        tid = getattr(_task_context, "task_id", None)
        return tid.hex() if tid else None

    def get_assigned_resources(self) -> dict[str, float]:
        return getattr(_task_context, "resources", {}) or {}


_task_context = threading.local()


def set_task_context(task_id: TaskID | None, actor_id: ActorID | None, resources: dict | None):
    _task_context.task_id = task_id
    _task_context.actor_id = actor_id
    _task_context.resources = resources


class Worker:
    def __init__(self):
        self.runtime = None  # LocalRuntime or cluster ClientRuntime
        self.job_id = JobID.nil()
        self.worker_id = WorkerID.nil()
        self.node_id = NodeID.nil()
        self.mode: str | None = None  # "local" | "cluster" | None

    @property
    def connected(self) -> bool:
        return self.runtime is not None

    def check_connected(self):
        if self.runtime is None:
            import ray_tpu

            ray_tpu.init()

    # thin delegation -------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        self.check_connected()
        return self.runtime.put(value)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        self.check_connected()
        return self.runtime.get(refs, timeout=timeout)


global_worker = Worker()


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(global_worker)
