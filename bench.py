"""Benchmark: Llama causal-LM training-step throughput, tokens/sec/chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

vs_baseline is FLOP-normalized against the reference north-star (BASELINE.md:
Llama-3-8B DDP fine-tune at ~3,300 tokens/sec per A100-class chip, i.e.
6·N·rate ≈ 1.59e14 training FLOP/s/chip): vs_baseline = (6·N·tokens_per_sec)
/ 1.59e14 — >1.0 means this chip trains more model-FLOPs per second than the
reference's A100 number.

Outage behavior: the TPU tunnel can be down for hours (backend init hangs).
The probe retries with backoff for a bounded window; if the chip stays
unreachable the bench emits the LAST GOOD TPU measurement tagged
``"tpu_unreachable": true`` — a comparable number for round tracking —
instead of an incomparable CPU-fallback figure.

Measurement strategy: the sweep is driven by the memory-model-guided
autotuner (ray_tpu/autotune) instead of a hand-enumerated candidate list.
The full config space (batch x remat — incl. per-layer save-lists — x
ZeRO-1 x grad accumulation x kernel block/chunk knobs) is priced by the
analytic HBM model; candidates predicted over the device budget are pruned
at analysis time (zero compile attempts spent on them), the survivors are
ranked, and the measurement budget goes to the best cached config FIRST
(banks a number — the r03 outage lesson) then the unexplored frontier.
Measured rows record predicted-vs-actual HBM (actual from the AOT
module's memory_analysis / hlo_stats liveness estimate) and persist in
AUTOTUNE_CACHE.json (per-machine, gitignored) so each round continues
the search; on a fresh checkout the cache re-seeds from the committed
BENCH_r*.json tried rows, which carry every measured config anyway.
"""

from __future__ import annotations

import json
import os
import sys
import time


A100_8B_TOKENS_PER_SEC = 3300.0
A100_8B_PARAMS = 8.03e9
BASELINE_FLOPS = 6.0 * A100_8B_PARAMS * A100_8B_TOKENS_PER_SEC  # 1.59e14

METRIC = "llama_1b_train_tokens_per_sec_per_chip"

# Fallback if no BENCH_r*.json with a real TPU measurement is found on disk
# (round 2 was the most recent chip-measured number when this was written).
_LAST_GOOD_DEFAULT = {"round": "r02", "value": 14860.1, "vs_baseline": 0.583}


def _last_good() -> dict:
    """Most recent REAL TPU measurement from the recorded rounds — scanned
    at runtime so the outage fallback can never go stale after a better
    round lands. Also considers PERF_TRAIN_TPU.json, which this harness
    writes on every successful mid-round TPU run: a measurement banked
    hours before the driver's end-of-round bench survives a tunnel outage
    at round close (the round-3 failure mode)."""
    import glob
    import re

    best = dict(_LAST_GOOD_DEFAULT)
    here = os.path.dirname(os.path.abspath(__file__))
    best_round = -1
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rnd = int(m.group(1))
        try:
            rec = json.load(open(path))
            rec = rec.get("parsed", rec)  # driver wraps the line
        except Exception:
            continue
        if (rec.get("metric") == METRIC and not rec.get("tpu_unreachable")
                and not rec.get("all_candidates_failed")
                and rec.get("value", 0) > 0 and rnd > best_round):
            best_round = rnd
            best = {"round": f"r{rnd:02d}", "value": rec["value"],
                    "vs_baseline": rec["vs_baseline"]}
    try:
        rec = json.load(open(os.path.join(here, "PERF_TRAIN_TPU.json")))
        if (rec.get("metric") == METRIC and rec.get("value", 0) > best["value"]
                and not rec.get("tpu_unreachable")):
            best = {"round": rec.get("round", "banked"),
                    "value": rec["value"],
                    "vs_baseline": rec["vs_baseline"]}
    except Exception:
        pass
    return best


def _bank(rec: dict) -> None:
    """Persist a successful TPU measurement next to the harness (see
    _last_good). ``value`` ratchets only within RUN VARIANCE (~1%): a
    re-run within 2% below the banked value keeps the banked number, but
    a genuinely slower measurement replaces it. ``last_run_value`` is
    ALWAYS the most recent run, so a ~1-2% regression hiding inside the
    variance band stays observable instead of vanishing behind a
    historical peak."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "PERF_TRAIN_TPU.json")
    rec = dict(rec)
    rec["last_run_value"] = rec.get("value")
    try:
        prev = json.load(open(path))
        if (prev.get("metric") == rec.get("metric")
                and rec.get("value", 0) < prev.get("value", 0)
                and rec.get("value", 0) >= prev.get("value", 0) * 0.98):
            # Within variance band: keep the better banked value (and its
            # derived fields, so the record stays internally consistent)
            # but still record this run in last_run_value.
            rec["value"] = prev["value"]
            rec["config"] = prev.get("config", rec.get("config"))
            if "vs_baseline" in prev:
                rec["vs_baseline"] = prev["vs_baseline"]
    except Exception:
        pass
    try:
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
    except Exception:
        pass


def _tpu_reachable(timeout: float = 90.0) -> bool:
    """Probe the TPU backend in a subprocess — backend init can hang
    indefinitely if the device tunnel is down, and it must not take the
    bench process with it."""
    import subprocess

    if os.environ.get("RTPU_BENCH_FORCE_NO_TPU") == "1":  # outage simulation
        return False
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert any(d.platform == 'tpu' for d in jax.devices())"],
            timeout=timeout, capture_output=True,
        )
        return r.returncode == 0
    except Exception:
        return False


def _wait_for_tpu(default_budget: float = 600.0) -> bool:
    """Retry the probe across a bounded window (driver budget), backing off
    between attempts — a transient tunnel blip must not discard the round's
    perf work. Shared by bench_serve.py."""
    budget = float(os.environ.get("RTPU_BENCH_PROBE_BUDGET_S",
                                  str(default_budget)))
    deadline = time.monotonic() + budget
    pause = 15.0
    while True:
        if _tpu_reachable():
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        time.sleep(min(pause, remaining))
        pause = min(pause * 2, 120.0)


def _emit(value: float, vs: float, extra: dict | None = None) -> None:
    rec = {"metric": METRIC, "value": round(value, 1),
           "unit": "tokens/sec/chip", "vs_baseline": round(vs, 3)}
    rec.update(extra or {})
    print(json.dumps(rec))


def _make_measure_fn(cfg, seq, steps, warmup):
    """One-candidate measurement closure for the autotune search driver:
    build the step under the candidate's kernel-env knobs, AOT-compile it
    (the compiled module's memory analysis is the 'actual' HBM the
    prediction is scored against), time the step, and clean up every live
    buffer so an OOM cannot poison the next candidate."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.parallel.hlo_stats import compiled_hbm_bytes
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train.optim import adamw_lowmem
    from ray_tpu.train.spmd import make_llama_train_step

    mesh = build_mesh(MeshSpec(dp=1), jax.devices()[:1])

    def measure(cand):
        state = compiled = None
        try:
            if cand.opt == "lowmem":
                opt = adamw_lowmem(3e-4, weight_decay=0.1)
            else:
                opt = optax.adamw(3e-4, weight_decay=0.1,
                                  mu_dtype=jnp.bfloat16)
            with cand.applied_env():
                step_fn, init_state, shard = make_llama_train_step(
                    cfg, mesh, optimizer=opt, attn_impl=cand.attn,
                    remat=cand.remat, **cand.step_options(),
                )
                state = init_state()
                rng = np.random.default_rng(0)
                tokens = shard(rng.integers(0, cfg.vocab_size,
                                            (cand.batch, seq),
                                            dtype=np.int32))
                targets = shard(np.roll(np.asarray(tokens), -1, axis=1))
                compiled = step_fn.lower(state, tokens, targets).compile()
            hbm, hbm_src = None, None
            try:
                hbm, hbm_src = compiled_hbm_bytes(compiled)
            except Exception:
                pass
            for _ in range(warmup):
                state, m = compiled(state, tokens, targets)
            jax.block_until_ready(m["loss"])
            t0 = time.perf_counter()
            for _ in range(steps):
                state, m = compiled(state, tokens, targets)
            jax.block_until_ready(m["loss"])
            dt = (time.perf_counter() - t0) / steps
            return {
                "tokens_per_sec": round(cand.batch * seq / dt, 1),
                "measured_hbm_gb": (round(hbm / (1 << 30), 3)
                                    if hbm else None),
                "hbm_source": hbm_src,
            }
        finally:
            # Drop every live buffer before the next candidate allocates —
            # a single OOM leaks ~9 GB of params/optimizer state otherwise.
            state = compiled = None  # noqa: F841
            for buf in jax.live_arrays():
                buf.delete()
            jax.clear_caches()

    return measure


def _seed_cache(cache, device_kind, geometry):
    """First autotuned round: seed the measurement cache from the recorded
    bench rounds (BENCH_r*.json tried rows + the banked PERF_TRAIN_TPU
    winner) so the champion is re-measured first and known-slow configs
    don't eat the measurement budget."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    rows: dict[str, float] = {}
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        try:
            rec = json.load(open(path))
            rec = rec.get("parsed", rec)
        except Exception:
            continue
        if rec.get("metric") != METRIC or rec.get("tpu_unreachable"):
            continue
        for row in rec.get("tried", []):
            tps = row.get("tokens_per_sec")
            if tps and tps > rows.get(row.get("config", ""), 0.0):
                rows[row["config"]] = tps
    try:
        rec = json.load(open(os.path.join(here, "PERF_TRAIN_TPU.json")))
        if rec.get("metric") == METRIC and rec.get("config") and \
                not rec.get("tpu_unreachable"):
            v = rec.get("value", 0.0)
            if v > rows.get(rec["config"], 0.0):
                rows[rec["config"]] = v
    except Exception:
        pass
    wrote = False
    for label, tps in rows.items():
        if cache.get(device_kind, geometry, label) is None:
            cache.put(device_kind, geometry, label,
                      {"tokens_per_sec": tps, "seeded": True}, flush=False)
            wrote = True
    if wrote:
        cache.flush()


def main() -> None:
    on_tpu = _wait_for_tpu()

    if not on_tpu:
        last = _last_good()
        _emit(last["value"], last["vs_baseline"],
              {"tpu_unreachable": True, "last_good_round": last["round"]})
        return

    import jax

    from ray_tpu.models.llama import LlamaConfig

    # ~1.1B-param geometry (Llama-3.2-1B-like), bf16, remat.
    cfg = LlamaConfig(
        vocab_size=32128, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
        max_seq_len=2048, tie_embeddings=True, dtype="bfloat16",
    )
    seq = 2048
    # Autotuned sweep (ray_tpu/autotune): the analytic HBM model prices
    # the full candidate space — batch x remat (incl. per-layer
    # save-lists) x zero1 x grad_accum x kernel block/chunk knobs — and
    # prunes over-budget configs before any compile (the r04 OOM rows
    # b16/attn, b8/dots, b4/dots+ are auto-pruned instead of hand-dropped).
    # The best cached config measures first (banks a number); the rest of
    # the measurement budget explores the predicted frontier.
    from ray_tpu.autotune import (
        autotune_train_configs,
        candidate_space,
        device_hbm_budget_bytes,
    )
    from ray_tpu.autotune.search import AutotuneCache, geometry_sig

    device_kind = jax.devices()[0].device_kind
    geometry = geometry_sig(cfg, seq, 1)
    cache = AutotuneCache()
    _seed_cache(cache, device_kind, geometry)
    res = autotune_train_configs(
        cfg, seq, candidate_space(cfg.num_layers),
        hbm_budget_bytes=device_hbm_budget_bytes(),
        measure_fn=_make_measure_fn(cfg, seq, steps=10, warmup=2),
        max_measure=int(os.environ.get("RTPU_BENCH_MAX_MEASURE", "6")),
        cache=cache, device_kind=device_kind,
    )
    tok_per_sec, config, tried = res.tokens_per_sec, res.winner, \
        res.tried_rows()
    autotune_info = {"space": res.space_size, "pruned": res.pruned,
                     "measured": res.measured, "failed": res.failed,
                     "analysis_seconds": res.analysis_seconds}

    # "tokens_per_sec" lands on a trace row only when a FRESH measurement
    # succeeded (cached-only rows carry cached_tokens_per_sec) — a winner
    # resolved purely from cache fallback must not be banked as fresh.
    fresh_ok = any("tokens_per_sec" in r for r in tried)
    if tok_per_sec <= 0 or not fresh_ok:
        # Every candidate failed even though the chip answered the probe —
        # that is a code/regression signal, NOT a tunnel outage. Emit the
        # last good number for tracking continuity but tag it honestly
        # (the per-candidate errors ride along for diagnosis).
        last = _last_good()
        _emit(last["value"], last["vs_baseline"],
              {"all_candidates_failed": True,
               "last_good_round": last["round"], "tried": tried,
               "autotune": autotune_info})
        return

    n_params = cfg.num_params()
    vs = (6.0 * n_params * tok_per_sec) / BASELINE_FLOPS
    _bank({"metric": METRIC, "value": round(tok_per_sec, 1),
           "unit": "tokens/sec/chip", "vs_baseline": round(vs, 3),
           "config": config, "ts": time.time()})
    _emit(tok_per_sec, vs, {"config": config, "tried": tried,
                            "autotune": autotune_info})


if __name__ == "__main__":
    main()
